"""Cut-through routing plane: whole-``FrameChunk`` batches from socket to
egress without per-frame Python objects.

PR 1's syscall attribution pinned the broker's forwarding floor on
per-message Python: the transports deliver parse batches (``FrameChunk``)
and egress is vectorized, but ``user_receive_loop``/``broker_receive_loop``
still peeled one frame at a time — recv → ``deserialize`` → hook →
``route_*`` — materializing a message object per frame before
``EgressBatch`` re-batched on the way out. This module closes that gap:

- a **route-plan kernel** (native/route_plan.cpp via
  ``pushcdn_tpu.native.routeplan``) scans a chunk's frame headers in place
  and matches them against a snapshot of the broker's routing state
  (interest bitmasks + DirectMap hash), returning per-peer fan-out index
  lists;
- the egress handoff is (buffer, offset, length) **slices of the pooled
  chunk**: a peer receiving a contiguous run of frames gets a zero-copy
  ``memoryview`` of the chunk (its wire framing is byte-identical to what
  arrived), with the chunk's pool permit transferred batch-wise via
  :class:`pushcdn_tpu.proto.limiter.BytesLease`; non-contiguous fan-out
  gathers with one C call into one owned buffer;
- **control frames** (Subscribe/Sync/auth/malformed) stop the plan at
  their index and take the existing scalar semantics, then planning
  resumes against the (possibly rebuilt) snapshot — so batch-vs-scalar
  behavior is identical even for mixes like ``[Subscribe(t),
  Broadcast(t)]`` landing in one chunk.

The scalar loops in ``handlers.py`` remain the correctness twin. Selection:
``PUSHCDN_ROUTE_CUTTHROUGH`` env (``auto``/``native``/``python``, with
``1``/``0`` aliases) or the ``--route-impl`` bench flag set
:data:`ROUTE_IMPL`; ``auto`` engages the native plane when the library
compiles AND the connection is eligible (no device plane — staged traffic
already routes in batched jitted steps — and the default no-op message
hook; a real hook must see every message, so those deployments stay
scalar). Observability: ``cdn_route_batch_*`` counters via ``/metrics``.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import TYPE_CHECKING, List, Optional

import numpy as np

from pushcdn_tpu.broker.tasks.handlers import (
    EgressBatch,
    _ingress_class,
    route_broadcast,
    route_direct,
)
from pushcdn_tpu.native import routeplan
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.def_ import no_hook
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import (
    Broadcast,
    Direct,
    LedgerSync,
    Subscribe,
    SubscribeFrom,
    TopicSync,
    Unsubscribe,
    UserSync,
    deserialize,
)
from pushcdn_tpu.proto.transport.base import FrameChunk
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")

# Routing implementation selector: "auto" (native cut-through when
# available and eligible), "native" (insist; still degrades with a
# warning if the library can't compile), "python" (scalar loops only).
# Mirrors the --delivery-impl precedent in bench.py.
_env = os.environ.get("PUSHCDN_ROUTE_CUTTHROUGH", "auto").strip().lower()
ROUTE_IMPL = {"1": "native", "0": "python", "true": "native",
              "false": "python", "": "auto"}.get(_env, _env)

_MODE_USER = 0    # user-origin: Direct anywhere, Broadcast users+brokers
_MODE_BROKER = 1  # broker-origin: local users only (loop prevention)

# Incremental route-state maintenance (ISSUE 7): Connections mutations
# append typed deltas to a bounded log, and _refresh applies the suffix
# IN PLACE to the native table (stored masks diffed, lazy-deleted index
# entries, tombstoned dmap) — O(delta), never O(users). "0" forces the
# pre-ISSUE-7 rebuild-per-invalidation behavior (the churn bench's
# baseline twin; the churn guard below is only live in that mode).
_env_inc = os.environ.get("PUSHCDN_ROUTE_INCREMENTAL", "1").strip().lower()
ROUTE_INCREMENTAL = _env_inc not in ("0", "false", "off")

# Rebuild churn guard — DEMOTED to last resort (ISSUE 7): with incremental
# deltas an invalidation costs O(delta), so the guard only arms on the
# rebuild-per-invalidation baseline path (ROUTE_INCREMENTAL off), where a
# snapshot rebuild is still O(users + brokers + DirectMap entries). When
# the previous snapshot amortized over fewer than _REBUILD_MIN_FRAMES
# planned frames, the next _REBUILD_BACKOFF invalidations route scalar
# instead of paying another full rebuild — the scalar path is always
# correct, so the guard only trades speed.
_REBUILD_MIN_FRAMES = 64
_REBUILD_BACKOFF = 16

# Compaction policy (checked every _COMPACT_CHECK_EVERY delta batches):
# lazy deletion and blob appends accrue garbage the plan loop must skip;
# a full rebuild purges it once it outweighs the live state.
_COMPACT_CHECK_EVERY = 64

_ZERO_MASK = np.zeros(routeplan.MASK_WORDS, np.uint64)  # reused, read-only

_warned_unavailable = False


def _inc_class_counts(classes, lens, frames_row, bytes_row) -> None:
    """Fold one plan's per-frame class array into the cdn_class_*
    counters (vectorized — one bincount per plan call, not per frame)."""
    frames, nbytes = flowclass.bincount_classes(classes, lens)
    for c in range(flowclass.N_CLASSES):
        n = int(frames[c])
        if n:
            frames_row[c].inc(n)
            bytes_row[c].inc(int(nbytes[c]))


def _note_link_classes(ident: str, fc, idx) -> None:
    """Bump the per-link conservation `sent` table for one broker-bound
    pair group — per class, off the plan's per-frame class array
    (ISSUE 20; one bincount per pair group, not per frame)."""
    if not ledger_mod.LEDGER.enabled:
        return
    if fc is None:
        ledger_mod.note_link_sent(ident, flowclass.LIVE, len(idx))
        return
    fci = np.asarray(fc)[idx]
    counts = np.bincount(fci[fci < flowclass.N_CLASSES],
                         minlength=flowclass.N_CLASSES)
    for c in range(flowclass.N_CLASSES):
        n = int(counts[c])
        if n:
            ledger_mod.note_link_sent(ident, c, n)


def _note_fate_classes(fate: str, reason: str, fc, idx) -> None:
    """Per-class terminal fates for one dropped pair group (plan path)."""
    if not ledger_mod.LEDGER.enabled:
        return
    if fc is None:
        ledger_mod.record_fate(fate, reason, flowclass.LIVE, len(idx))
        return
    fci = np.asarray(fc)[idx]
    counts = np.bincount(np.minimum(fci, flowclass.N_CLASSES),
                         minlength=flowclass.N_CLASSES + 1)
    for c in range(flowclass.N_CLASSES):
        n = int(counts[c])
        if n:
            ledger_mod.record_fate(fate, reason, c, n)
    n = int(counts[flowclass.N_CLASSES])
    if n:  # CLASS_NONE / out-of-range frames still get their fate
        ledger_mod.record_fate(fate, reason, flowclass.CLASS_NONE, n)


def acquire(broker: "Broker", hook) -> Optional["RouteState"]:
    """The receive loops' entry: the broker's shared cut-through state, or
    None when the scalar path should run (implementation forced to python,
    native kernel unavailable, a non-default message hook, or a device
    plane owning the eligible traffic)."""
    global _warned_unavailable
    impl = ROUTE_IMPL
    if impl not in ("auto", "native"):
        return None
    if hook is not no_hook or broker.device_plane is not None:
        return None
    durable = broker.durable
    if durable is not None and durable.enabled \
            and broker.connections.num_shards > 1:
        # sharded durable topics route scalar: the owner shard's ordered
        # drainer pins the replay-vs-live handover, and a chunk plan's
        # egress would bypass it (unsharded durable brokers keep the
        # cut-through plane — the retention scan rides the plan seam)
        return None
    state = getattr(broker, "_route_state", None)
    if state is None:
        planner = routeplan.RoutePlanner.create()
        if planner is None:
            if impl == "native" and not _warned_unavailable:
                _warned_unavailable = True
                logger.warning("route cut-through requested but the native "
                               "kernel is unavailable; using scalar routing")
            return None
        state = RouteState(broker, planner)
        broker._route_state = state
    return state


class RouteState:
    """Shared per-broker snapshot + planner (both receive loops use it).

    The snapshot keys on ``Connections.interest_version``, which every
    routing-state mutation bumps (subscriptions, membership, DirectMap
    merges) — the same token the scalar path's per-batch interest caches
    validate against. The version is revalidated before EVERY plan call
    (egress awaits can park the drain while another task mutates routing
    state), so a stale snapshot can never route a frame the scalar
    path's per-message version check would have routed differently.

    Maintenance is INCREMENTAL (ISSUE 7): peers occupy stable SLOTS
    (free-listed; ``n_users``/``n_brokers`` passed to the native build are
    capacities), and ``_refresh`` applies the ``Connections.route_log``
    suffix in place — each typed record names an entity (user / broker /
    DirectMap key) that is re-resolved against CURRENT Connections state,
    so application is order-insensitive and O(dirty entities). Full
    rebuilds remain only as the fallback: first build, version gap (log
    trimmed past our cursor), delta overflow (suffix longer than a
    rebuild costs), slot-capacity growth, and periodic compaction (lazy
    deletions / dmap tombstones / blob garbage crossed the purge
    threshold) — each counted under
    ``cdn_route_table_rebuilds{reason=...}``.
    """

    __slots__ = ("broker", "planner", "version", "usable",
                 "user_cap", "user_slot", "slot_user", "user_free",
                 "user_shard",
                 "broker_cap", "broker_slot", "slot_broker", "broker_free",
                 "broker_shard",
                 "dmap_mirror", "owner_keys", "log_seq",
                 "deltas_applied", "rebuild_counts", "last_delta_apply_s",
                 "_applies_since_compact_check", "_rebuild_reason",
                 "_frames_since_rebuild", "_skip_rebuilds",
                 "built_at", "_pump_state", "_pump_off")

    def __init__(self, broker: "Broker", planner):
        self.broker = broker
        self.planner = planner
        self.version = -1
        # peer slot space: users [0, user_cap), brokers [user_cap,
        # user_cap + broker_cap). The planner only distinguishes users
        # (< n_users == user_cap) from brokers — sibling-shard users count
        # as users so broker-origin frames still reach them; per-slot
        # shard arrays say whether egress is local or rides the ring.
        self.user_cap = 0
        self.user_slot: dict = {}          # key -> slot
        self.slot_user: List[Optional[bytes]] = []
        self.user_free: List[int] = []
        self.user_shard: List[int] = []    # == conns.shard_id -> local
        self.broker_cap = 0
        self.broker_slot: dict = {}        # ident -> slot (0-based)
        self.slot_broker: List[Optional[str]] = []
        self.broker_free: List[int] = []
        self.broker_shard: List[Optional[int]] = []  # None -> local link
        # DirectMap mirror + owner inverse index: which snapshot keys an
        # owner's entries resolve through, so a mesh link flap re-resolves
        # exactly its own keys (never a full-map scan)
        self.dmap_mirror: dict = {}        # key bytes -> owner str
        self.owner_keys: dict = {}         # owner str -> set of key bytes
        self.log_seq = 0                   # route_log cursor (next unseen)
        self.deltas_applied = 0
        self.rebuild_counts: dict = {}
        self.last_delta_apply_s: Optional[float] = None
        self._applies_since_compact_check = 0
        self._rebuild_reason: Optional[str] = None
        self.usable = True
        # cold start counts as amortized: the first build must not arm
        # the churn backoff
        self._frames_since_rebuild = 1 << 30
        self._skip_rebuilds = 0
        self.built_at: Optional[float] = None  # monotonic, last rebuild
        # fused data-plane pump (ISSUE 15): PumpState on this loop's
        # uring engine, or None. _pump_off latches when the composition
        # can never engage (env off / asyncio io impl / lib missing);
        # a transient None (engine not up yet) keeps retrying.
        self._pump_state = None
        self._pump_off = False

    def summary(self) -> dict:
        """Operator-facing snapshot state for ``/debug/topology``."""
        conns = self.broker.connections
        return {
            "usable": self.usable,
            "incremental": ROUTE_INCREMENTAL,
            "snapshot_version": self.version,
            "interest_version": conns.interest_version,
            "snapshot_age_s": (round(time.monotonic() - self.built_at, 3)
                               if self.built_at is not None else None),
            "churn_guard_skips_left": self._skip_rebuilds,
            "frames_since_rebuild": min(self._frames_since_rebuild, 1 << 30),
            "snapshot_users": len(self.user_slot),
            "snapshot_brokers": len(self.broker_slot),
            "slot_capacity": {"users": self.user_cap,
                              "brokers": self.broker_cap},
            "deltas_applied": self.deltas_applied,
            "last_delta_apply_s": self.last_delta_apply_s,
            "delta_log": {"start": conns.route_log_start,
                          "next": conns.route_log_next,
                          "cursor": self.log_seq},
            "rebuilds": dict(self.rebuild_counts),
            "index": self.planner.stats() if self.usable else None,
            "pump": (self._pump_state.summary()
                     if self._pump_state is not None
                     and not self._pump_state.closed else None),
        }

    def _get_pump(self):
        """The fused pump for this loop's uring engine, engaging lazily
        (the engine exists only once a uring transport served a
        connection). Returns None when the composition cannot engage —
        every such call is counted by the pump module, never silent."""
        ps = self._pump_state
        if ps is not None:
            if not ps.closed:
                return ps
            self._pump_state = None  # engine died; it may come back
        if self._pump_off:
            return None
        from pushcdn_tpu.proto.transport import pump as pump_mod
        ok, _why = pump_mod.resolve_pump()
        if not ok:
            # permanent for this process config: env off, io impl not
            # uring, or a native layer failed to build/probe
            self._pump_off = True
            return None
        from pushcdn_tpu.proto.transport import uring as umod
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return None
        ent = umod.UringEngine._engines.get(id(loop))
        eng = ent[1] if ent is not None else None
        if eng is None or eng.closed:
            return None  # no engine on this loop (yet): plain cut-through
        ps = pump_mod.PumpState.create(eng, self.broker, owner=self)
        self._pump_state = ps  # None if another broker owns the engine
        if ps is None:
            self._pump_off = True
        return ps

    # -- snapshot ------------------------------------------------------------

    def _refresh(self) -> bool:
        conns = self.broker.connections
        if self.version == conns.interest_version and self.usable:
            return True
        if ROUTE_INCREMENTAL and self.usable and self.version >= 0:
            # incremental path: apply the route-log suffix in place
            if self.log_seq < conns.route_log_start:
                return self._storm_rebuild("version_gap")
            pending = list(itertools.islice(
                conns.route_log, self.log_seq - conns.route_log_start,
                None))
            # past this many dirty entities a rebuild is the cheaper
            # O(users) operation (and resets slot packing for free)
            threshold = max(256, (len(self.user_slot)
                                  + len(self.broker_slot)) // 2)
            if len(pending) > threshold:
                return self._storm_rebuild("delta_overflow")
            if self._apply_deltas(pending):
                self.version = conns.interest_version
                self.log_seq = conns.route_log_next
                self._applies_since_compact_check += 1
                if self._applies_since_compact_check \
                        >= _COMPACT_CHECK_EVERY:
                    self._applies_since_compact_check = 0
                    if self._needs_compaction():
                        return self._rebuild("compaction")
                return True
            return self._rebuild(self._rebuild_reason or "growth")
        # full-rebuild path: first build, incremental disabled, or the
        # previous build failed. Only HERE does the (demoted) churn guard
        # apply — the rebuild-per-invalidation baseline's backoff.
        if self._skip_rebuilds > 0:
            self._skip_rebuilds -= 1
            return False
        if self.version < 0:
            reason = "first_build"
        elif not self.usable:
            reason = "retry"
        else:
            reason = "incremental_disabled"
        return self._rebuild(reason)

    def _storm_rebuild(self, reason: str) -> bool:
        """Fallback rebuild for the two EXTERNALLY-DRIVEN reasons
        (version gap / delta overflow): unlike growth or compaction —
        which are self-limiting by construction (capacity headroom grows
        25% per rebuild; a rebuild zeroes the garbage counters) — these
        recur at whatever rate the outside churn sustains, so the
        demoted churn guard still throttles them as the last resort: a
        rebuild that never amortized (< _REBUILD_MIN_FRAMES planned
        since) sends the next _REBUILD_BACKOFF invalidations to the
        always-correct scalar path instead of paying back-to-back
        O(users) rebuilds that would stall the loop."""
        if self._skip_rebuilds > 0:
            self._skip_rebuilds -= 1
            return False
        return self._rebuild(reason)

    def _needs_compaction(self) -> bool:
        """Garbage-vs-live thresholds over the native occupancy counters:
        lazy-deleted / duplicated index entries, dmap tombstones, and
        key-blob garbage are all purged by one rebuild."""
        s = self.planner.stats()
        return (s["list_entries"] > 2 * s["live_subs"] + 1024
                or s["dmap_tombstones"] > s["dmap_live"] + 64
                or s["keys_blob_garbage"]
                > s["keys_blob_bytes"] // 2 + 4096)

    def _rebuild(self, reason: str) -> bool:
        """Full snapshot rebuild (the fallback + compactor). Slots are
        re-packed densely with free-list headroom so steady growth does
        not rebuild per connection."""
        conns = self.broker.connections
        local_users = list(conns.users.keys())
        # parting users keep their interest rows through the migration
        # grace (late-broadcast chase, see Connections.remove_user) —
        # keep their slots plannable across a rebuild too
        local_users += [k for k in conns.parting if k not in conns.users]
        remote_users = list(conns.remote_user_shard.keys())
        users = local_users + remote_users
        local_brokers = list(conns.brokers.keys())
        remote_brokers = [ident for ident in conns.remote_broker_shard
                          if ident not in conns.brokers]
        brokers = local_brokers + remote_brokers
        n_u, n_b = len(users), len(brokers)
        user_cap = max(16, n_u + max(n_u // 4, 64))
        broker_cap = max(8, n_b + max(n_b // 4, 16))
        peer_masks = np.zeros((user_cap + broker_cap, routeplan.MASK_WORDS),
                              np.uint64)
        local_shard = conns.shard_id
        slot_user: List[Optional[bytes]] = [None] * user_cap
        user_shard = [local_shard] * user_cap
        user_slot: dict = {}
        for i, key in enumerate(users):
            topics = conns.user_topics.get_values_of_key(key)
            if topics:
                peer_masks[i] = routeplan.topic_mask(topics)
            slot_user[i] = key
            user_slot[key] = i
            if i >= len(local_users):
                user_shard[i] = conns.remote_user_shard[key]
        slot_broker: List[Optional[str]] = [None] * broker_cap
        broker_shard: List[Optional[int]] = [None] * broker_cap
        broker_slot: dict = {}
        for j, ident in enumerate(brokers):
            topics = conns.broker_topics.get_values_of_key(ident)
            if topics:
                peer_masks[user_cap + j] = routeplan.topic_mask(topics)
            slot_broker[j] = ident
            broker_slot[ident] = j
            if j >= len(local_brokers):
                broker_shard[j] = conns.remote_broker_shard[ident]
        valid = routeplan.topic_mask(self.broker.run_def.topics.valid)
        identity = conns.identity
        dmap: dict = {}
        mirror: dict = {}
        owner_keys: dict = {}
        for key, owner in conns.direct_map.items():
            bkey = bytes(key)
            mirror[bkey] = owner
            if owner == identity:
                peer = user_slot.get(key)
            else:
                owner_keys.setdefault(owner, set()).add(bkey)
                b = broker_slot.get(owner)
                peer = None if b is None else user_cap + b
            if peer is not None:
                dmap[bkey] = peer
            # unresolvable owner (user/broker not connected): omitted — a
            # plan miss drops the frame, exactly like the scalar flush
            # finding no connection
        # sibling-shard users aren't in this worker's DirectMap replica
        # (only shard 0 mirrors the claims for the mesh) — add them so
        # Direct frames plan straight onto the ring
        for key in remote_users:
            dmap.setdefault(bytes(key), user_slot[key])
        dkeys = list(dmap.keys())
        owners = list(dmap.values())
        self.usable = self.planner.build(
            user_cap, broker_cap, valid, peer_masks, dkeys,
            np.asarray(owners, np.int32))
        if self.usable:
            # mirror the flow-class taxonomy into the native table so the
            # plan (and the fused pump) classes frames exactly like the
            # scalar senders; deployment config, so every rebuild restores
            # the same map
            self.planner.set_classes(flowclass.active_table())
            self.version = conns.interest_version
            self.log_seq = conns.route_log_next
            self.user_cap = user_cap
            self.user_slot = user_slot
            self.slot_user = slot_user
            self.user_free = list(range(user_cap - 1, n_u - 1, -1))
            self.user_shard = user_shard
            self.broker_cap = broker_cap
            self.broker_slot = broker_slot
            self.slot_broker = slot_broker
            self.broker_free = list(range(broker_cap - 1, n_b - 1, -1))
            self.broker_shard = broker_shard
            self.dmap_mirror = mirror
            self.owner_keys = owner_keys
            self._rebuild_reason = None
            self._applies_since_compact_check = 0
            self.built_at = time.monotonic()
            self.rebuild_counts[reason] = \
                self.rebuild_counts.get(reason, 0) + 1
            metrics_mod.ROUTE_TABLE_REBUILDS.labels(reason=reason).inc()
            if self._frames_since_rebuild < _REBUILD_MIN_FRAMES \
                    and (not ROUTE_INCREMENTAL
                         or reason in ("version_gap", "delta_overflow")):
                self._skip_rebuilds = _REBUILD_BACKOFF
            self._frames_since_rebuild = 0
        return self.usable

    def _resolve_dmap_peer(self, bkey: bytes, owner: Optional[str],
                           local_shard: int) -> Optional[int]:
        """Current peer slot a DirectMap key routes to, mirroring the
        rebuild's resolution rules exactly: the owner wins when resolvable
        (self -> the user's own slot, remote -> the owning broker's link
        slot), and a sibling-shard RESIDENT without a resolvable owner
        gets the membership-implied entry straight onto the ring."""
        if owner == self.broker.connections.identity:
            return self.user_slot.get(bkey)
        if owner is not None:
            b = self.broker_slot.get(owner)
            if b is not None:
                return self.user_cap + b
        slot = self.user_slot.get(bkey)
        if slot is not None and self.user_shard[slot] != local_shard:
            return slot
        return None

    def _apply_deltas(self, records: list) -> bool:
        """Apply one route-log suffix IN PLACE. Re-resolves every named
        entity against current Connections state (order-insensitive), then
        ships the whole batch to the native table in ONE call. Returns
        False when a rebuild is required (slot growth, native alloc
        failure) — ``_rebuild_reason`` says why."""
        conns = self.broker.connections
        t0 = time.perf_counter()
        dirty_users: set = set()
        dirty_brokers: set = set()
        dirty_keys: set = set()
        for kind, ident in records:
            if kind == "user":
                dirty_users.add(ident)
            elif kind == "broker":
                dirty_brokers.add(ident)
            else:
                dirty_keys.add(ident)
        upd_peers: List[int] = []
        upd_masks: List[np.ndarray] = []
        # brokers first: a link transition re-resolves exactly the keys
        # its DirectMap entries own (the owner inverse index)
        for ident in dirty_brokers:
            slot = self.broker_slot.get(ident)
            if ident in conns.brokers:
                shard: Optional[int] = None
            else:
                shard = conns.remote_broker_shard.get(ident)
                if shard is None:  # link gone everywhere: free the slot
                    if slot is not None:
                        del self.broker_slot[ident]
                        self.slot_broker[slot] = None
                        self.broker_shard[slot] = None
                        self.broker_free.append(slot)
                        upd_peers.append(self.user_cap + slot)
                        upd_masks.append(_ZERO_MASK)
                        dirty_keys.update(self.owner_keys.get(ident, ()))
                    continue
            if slot is None:
                if not self.broker_free:
                    self._rebuild_reason = "growth"
                    return False
                slot = self.broker_free.pop()
                self.broker_slot[ident] = slot
                self.slot_broker[slot] = ident
                dirty_keys.update(self.owner_keys.get(ident, ()))
            self.broker_shard[slot] = shard
            upd_peers.append(self.user_cap + slot)
            upd_masks.append(routeplan.topic_mask(
                conns.broker_topics.get_values_of_key(ident)))
        local_shard = conns.shard_id
        for key in dirty_users:
            slot = self.user_slot.get(key)
            if key in conns.users:
                shard = local_shard
            else:
                shard = conns.remote_user_shard.get(key)
            if shard is None:  # gone from every shard: free the slot
                if slot is not None:
                    del self.user_slot[key]
                    self.slot_user[slot] = None
                    self.user_free.append(slot)
                    upd_peers.append(slot)
                    upd_masks.append(_ZERO_MASK)
                    dirty_keys.add(key)
                continue
            if slot is None:
                if not self.user_free:
                    self._rebuild_reason = "growth"
                    return False
                slot = self.user_free.pop()
                self.user_slot[key] = slot
                self.slot_user[slot] = key
                dirty_keys.add(key)
            elif self.user_shard[slot] != shard:
                # residency flip: the membership-implied dmap entry may
                # appear/disappear with it
                dirty_keys.add(key)
            self.user_shard[slot] = shard
            upd_peers.append(slot)
            upd_masks.append(routeplan.topic_mask(
                conns.user_topics.get_values_of_key(key)))
        dkeys: List[bytes] = []
        downers: List[int] = []
        identity = conns.identity
        for key in dirty_keys:
            bkey = bytes(key)
            new_owner = conns.direct_map.get(key)
            old_owner = self.dmap_mirror.get(bkey)
            if old_owner != new_owner:
                if old_owner is not None and old_owner != identity:
                    keyset = self.owner_keys.get(old_owner)
                    if keyset is not None:
                        keyset.discard(bkey)
                        if not keyset:
                            del self.owner_keys[old_owner]
                if new_owner is None:
                    self.dmap_mirror.pop(bkey, None)
                else:
                    self.dmap_mirror[bkey] = new_owner
                    if new_owner != identity:
                        self.owner_keys.setdefault(new_owner,
                                                   set()).add(bkey)
            peer = self._resolve_dmap_peer(bkey, new_owner, local_shard)
            dkeys.append(bkey)
            downers.append(-1 if peer is None else peer)
        if upd_peers or dkeys:
            if not self.planner.apply(upd_peers, upd_masks, dkeys,
                                      downers):
                self._rebuild_reason = "retry"
                return False
        n = len(records)
        self.deltas_applied += n
        dt = time.perf_counter() - t0
        self.last_delta_apply_s = round(dt, 6)
        if n:
            metrics_mod.ROUTE_DELTAS_APPLIED.inc(n)
        metrics_mod.ROUTE_DELTA_APPLY_SECONDS.observe(dt)
        return True

    # -- egress --------------------------------------------------------------

    @staticmethod
    def _ledger_ingress_fold(fc, pos: int, consumed: int, buf,
                             offs, lens, peer) -> None:
        """Fold one plan call's consumed frames into the ledger's ingress
        (and, for a mesh link, per-link recv) tables — one bincount for
        the classed frames; routed-nowhere frames (out_class 255) resolve
        their wire class per frame so both link ends count identically,
        and take their terminal fate here (no_interest for a pruned-empty
        Broadcast, no_route for an unknown-recipient Direct)."""
        if not ledger_mod.LEDGER.enabled or not consumed:
            return
        fci = np.asarray(fc[pos:pos + consumed])
        counts = np.bincount(
            np.minimum(fci, flowclass.N_CLASSES),
            minlength=flowclass.N_CLASSES + 1)
        for c in range(flowclass.N_CLASSES):
            n = int(counts[c])
            if n:
                ledger_mod.note_ingress(c, n, peer)
        if int(counts[flowclass.N_CLASSES]):
            mv = memoryview(buf)
            for i in np.nonzero(fci >= flowclass.N_CLASSES)[0].tolist():
                j = pos + i
                data = mv[int(offs[j]):int(offs[j]) + int(lens[j])]
                cls = flowclass.frame_class(data)
                ledger_mod.note_ingress(cls, 1, peer)
                kind = data[0] if len(data) else 0
                reason = ("no_interest" if (kind & 0x7F) == 5
                          else "no_route")
                ledger_mod.record_fate("dropped", reason, cls)

    async def _send_plan(self, chunk: FrameChunk, offs: np.ndarray,
                         lens: np.ndarray, peers: np.ndarray,
                         frames: np.ndarray, fc=None) -> None:
        """Hand one plan's fan-out to the per-peer writers. Pairs arrive in
        frame order; a stable sort groups them per peer without disturbing
        per-(sender→receiver) frame order. Failure ⇒ removal, exactly like
        ``EgressBatch.flush``. ``fc`` is the plan's per-frame class array
        (absolute indices) — dir=out accounting happens here at the pair
        level, so the writer stamps below carry nframes=0/nbytes=0."""
        if len(peers) == 0:
            return
        if fc is not None:
            _inc_class_counts(fc[frames], lens[frames],
                              metrics_mod.CLASS_FRAMES_OUT,
                              metrics_mod.CLASS_BYTES_OUT)
        broker = self.broker
        # Phase 1 — SYNCHRONOUS build: resolve peer indices against the
        # snapshot lists and assemble every per-peer stream before any
        # await. The pair arrays are views into the planner's shared
        # scratch and the index→key lists are replaced on rebuild; a
        # concurrent drain (another connection's receive loop running
        # during a send await) may re-plan or rebuild, so nothing below
        # the first await may touch planner scratch or snapshot state.
        user_cap = self.user_cap
        local_shard = broker.connections.shard_id
        slot_user = self.slot_user
        slot_broker = self.slot_broker
        user_shard = self.user_shard
        broker_shard = self.broker_shard
        order = np.argsort(peers, kind="stable")
        speers = peers[order]
        sframes = frames[order]
        bounds = np.nonzero(np.diff(speers))[0] + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(speers)]))
        buf = chunk.buf
        mv = None
        sends: list = []  # (is_user, key_or_ident, data, owner, n, cls)
        ring: Optional[dict] = None  # shard -> [(kind, ident, idx array)]
        for s, e in zip(starts.tolist(), ends.tolist()):
            peer = int(speers[s])
            idx = sframes[s:e]
            if peer < user_cap:
                key = slot_user[peer]
                if key is None:
                    # freed slot raced the plan: drop (defensive)
                    _note_fate_classes("dropped", "no_route", fc, idx)
                    continue
                shard = user_shard[peer]
                if shard != local_shard:
                    # sibling-shard user: cross-shard handoff (collected
                    # per shard; written to the ring below, still inside
                    # the synchronous phase — idx is COPIED because the
                    # pair arrays are reusable planner scratch)
                    if ring is None:
                        ring = {}
                    ring.setdefault(shard, []).append(
                        (0, bytes(key), idx.copy()))
                    continue
                target = (True, key)
            else:
                b = peer - user_cap
                ident = slot_broker[b]
                if ident is None:
                    # freed slot: drop (defensive)
                    _note_fate_classes("dropped", "no_route", fc, idx)
                    continue
                # mesh-bound pair group: the per-link conservation table
                # counts here (the routing decision) whether the frames
                # ride this shard's link or a sibling's ring
                _note_link_classes(ident, fc, idx)
                shard = broker_shard[b]
                if shard is not None:
                    if ring is None:
                        ring = {}
                    ring.setdefault(shard, []).append(
                        (1, ident.encode(), idx.copy()))
                    continue
                target = (False, ident)
            first, last = int(idx[0]), int(idx[-1])
            if last - first + 1 == len(idx):
                # contiguous run: the chunk's own bytes ARE the wire
                # stream (frames sit back-to-back, length-prefixed) —
                # zero-copy view + batch-wise permit lease
                if mv is None:
                    mv = memoryview(buf)
                data = mv[int(offs[first]) - 4:
                          int(offs[last]) + int(lens[last])]
                owner = chunk.lease()
            else:
                data = self.planner.gather(buf, offs, lens, idx)
                owner = None
                if data is None:  # can't happen on in-range indices
                    continue
            # queue-delay attribution class: the batch's first frame's
            # (volume was already counted pair-level above)
            cls = int(fc[first]) & 3 if fc is not None else flowclass.LIVE
            sends.append((*target, data, owner, len(idx), cls))
        if ring is not None:
            # still phase 1 (synchronous): the ring write copies the wire
            # bytes straight out of the pooled chunk into shared memory —
            # pre-encoded chunks + per-peer index lists, no per-frame
            # message objects, no re-serialization (ISSUE 6)
            broker.shard_runtime.handoff_chunk(buf, offs, lens, ring)
        # Phase 2 — sends (may await). Connections are looked up by
        # stable identity here, like the scalar flush: a peer that left
        # mid-batch drops its frames; failure ⇒ removal.
        for is_user_peer, key, data, owner, n_frames, cls in sends:
            if is_user_peer:
                conn = broker.connections.get_user_connection(key)
            else:
                conn = broker.connections.get_broker_connection(key)
            if conn is None:
                # peer left since the plan: drop (scalar parity)
                ledger_mod.record_fate("dropped", "no_route", cls, n_frames)
                continue
            (metrics_mod.EGRESS_FRAMES_USER if is_user_peer
             else metrics_mod.EGRESS_FRAMES_BROKER).inc(n_frames)
            try:
                await conn.send_encoded(data, owner, cls=cls,
                                        nframes=0, nbytes=0,
                                        count=n_frames)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                if is_user_peer:
                    logger.info("send to user %s failed (%r); removing",
                                mnemonic(key), exc)
                    broker.connections.remove_user(key, reason="send failed")
                else:
                    logger.info("send to broker %s failed (%r); removing",
                                key, exc)
                    broker.connections.remove_broker(key,
                                                     reason="send failed")
                broker.update_metrics()

    # -- scalar twins for residual / depth-1 traffic -------------------------

    def _route_one_scalar(self, sender_id, message, raw: Bytes,
                          is_user: bool, egress: EgressBatch,
                          interest_cache: dict, conn=None) -> bool:
        """Route ONE already-deserialized message with the scalar rules
        (no device plane, no-op hook — both guaranteed by ``acquire``).
        Returns False when the sender must be disconnected. ``conn`` is
        the sender's own connection (the admission token bucket's seat)."""
        broker = self.broker
        topics_space = broker.run_def.topics
        ledger_mod.note_ingress(_ingress_class(message),
                                peer=None if is_user else sender_id)
        if isinstance(message, Direct):
            tr = message.trace
            if tr is not None:
                trace_mod.emit("ingress", tr, "residual")
            a0 = egress.appended
            route_direct(broker, message.recipient, raw,
                         to_user_only=not is_user, egress=egress)
            if tr is not None:
                # a plan span tagged "dropped" (and no egress span) means
                # the broker itself dropped the message — unknown
                # recipient / to-user-only suppression, not a downstream
                # loss
                if egress.appended > a0:
                    trace_mod.emit("plan", tr, "residual")
                    egress.note_trace(tr)
                else:
                    trace_mod.emit("plan", tr, "dropped")
        elif isinstance(message, Broadcast):
            tr = message.trace
            if tr is not None:
                trace_mod.emit("ingress", tr, "residual")
            a0 = egress.appended
            pruned, _bad = topics_space.prune(message.topics)
            if pruned:
                # durable stamp rides the same synchronous block as the
                # route decision (scalar-twin parity with handlers.py);
                # on_publish always returns True here — acquire() routes
                # sharded durable brokers scalar, so this plane only sees
                # the unsharded retain-and-route-normally case
                durable = broker.durable
                if durable is None or durable.on_publish(
                        pruned, message, raw, to_users_only=not is_user):
                    route_broadcast(broker, pruned, raw,
                                    to_users_only=not is_user,
                                    egress=egress,
                                    interest_cache=interest_cache,
                                    raw_topics=message.topics)
            if tr is not None:
                if egress.appended > a0:
                    trace_mod.emit("plan", tr, "residual")
                    egress.note_trace(tr)
                else:
                    trace_mod.emit("plan", tr, "dropped")
        elif is_user and isinstance(message, Subscribe):
            pruned, bad = topics_space.prune(message.topics)
            if bad:
                return False  # unknown topic ⇒ disconnect (scalar parity)
            adm = broker.admission
            if adm is not None and not adm.allow_subscribe(conn):
                adm.shed_subscribe(sender_id, conn, egress)  # ISSUE 7
            else:
                broker.connections.subscribe_user_to(sender_id, pruned)
        elif is_user and isinstance(message, Unsubscribe):
            adm = broker.admission
            if adm is not None and not adm.allow_subscribe(conn):
                adm.shed_subscribe(sender_id, conn, egress)
            else:
                pruned, _bad = topics_space.prune(message.topics)
                broker.connections.unsubscribe_user_from(sender_id, pruned)
        elif is_user and isinstance(message, SubscribeFrom):
            # durable replay subscribe (ISSUE 14), scalar-twin parity
            adm = broker.admission
            if adm is not None and not adm.allow_subscribe(conn):
                adm.shed_subscribe(sender_id, conn, egress)
            else:
                durable = broker.durable
                if durable is None or not durable.handle_subscribe_from(
                        sender_id, message, conn):
                    return False
        elif not is_user and isinstance(message, UserSync):
            broker.connections.apply_user_sync(message.payload)
            broker.update_metrics()
        elif not is_user and isinstance(message, TopicSync):
            broker.connections.apply_topic_sync(sender_id, message.payload)
        elif not is_user and isinstance(message, LedgerSync):
            # peer's conservation balance sheet (ISSUE 20; scalar-twin
            # parity with broker_receive_loop — never link-fatal)
            import json
            try:
                sheet = json.loads(bytes(message.payload))
            except (ValueError, UnicodeDecodeError):
                sheet = None
            if sheet is not None:
                ledger_mod.LEDGER.note_peer_sheet(sender_id, sheet)
        else:
            # users may not send auth/sync post-handshake; brokers may not
            # send auth/subscribe — disconnect (scalar parity, including
            # the broker-loop diagnostic; the user loop logs nothing here)
            if not is_user:
                logger.warning("broker %s sent unexpected %s; dropping link",
                               sender_id, type(message).__name__)
            return False
        return True

    def _log_malformed(self, sender_id, is_user: bool, conn) -> None:
        """The scalar loops' malformed-frame diagnostics, verbatim (plus a
        flight-recorder event, so the disconnect dump shows the trigger).
        ``conn`` is the drain's OWN connection object — never resolved by
        identity here, because a quick reconnect swaps the map entry and
        the event would land on (and arm) the innocent new link."""
        if is_user:
            logger.info("user %s sent malformed frame; disconnecting",
                        mnemonic(sender_id))
        else:
            logger.warning("broker %s sent malformed frame; dropping link",
                           sender_id)
        if conn is not None:
            conn.flightrec.record("malformed-frame", abnormal=True)
        ledger_mod.record_fate("dropped", "malformed",
                               flowclass.CLASS_NONE)

    # -- drains --------------------------------------------------------------

    async def route_drain(self, sender_id, items: list,
                          is_user: bool, conn=None) -> bool:
        """Route one ``recv_frames()`` drain (a mix of :class:`FrameChunk`
        batches and depth-1 :class:`Bytes` frames), preserving arrival
        order end to end. Returns False when the sender must be
        disconnected; every item's pool permit is settled either way.
        ``conn`` is the sender's own connection (flight-recorder seat for
        malformed-frame events)."""
        mode = _MODE_USER if is_user else _MODE_BROKER
        egress = EgressBatch(self.broker)
        interest_cache: dict = {}
        alive = True
        idx = 0  # items[idx:] are the ones whose release is still owed
        try:
            while idx < len(items):
                item = items[idx]
                idx += 1
                if type(item) is not FrameChunk:
                    # depth-1 frame (the latency regime): scalar-route it
                    # through the accumulating egress (which clones), then
                    # settle its permit here
                    metrics_mod.ROUTE_RESIDUAL_FRAMES.inc()
                    try:
                        try:
                            message = deserialize(item.data)
                        except Error:
                            self._log_malformed(sender_id, is_user, conn)
                            alive = False
                        else:
                            alive = self._route_one_scalar(
                                sender_id, message, item, is_user, egress,
                                interest_cache, conn)
                    finally:
                        item.release()
                    if not alive:
                        break
                    continue
                # handoff guard: until _route_chunk/_chunk_scalar take
                # ownership (they release in their finally), an exception
                # or cancellation here must settle this chunk's permit —
                # the outer finally only covers items[idx:]
                try:
                    usable = self._refresh()
                    if usable:
                        # a chunk's plan enqueues per-peer streams
                        # immediately; flush accumulated singles first so
                        # per-peer order follows arrival order
                        await egress.flush()
                except BaseException:
                    item.release()
                    raise
                if usable:
                    alive = await self._route_chunk(sender_id, item, mode,
                                                    is_user, egress,
                                                    interest_cache, conn)
                else:
                    # snapshot build failed (allocation): scalar-route the
                    # chunk frame by frame — correctness over speed
                    alive = await self._chunk_scalar(sender_id, item,
                                                     is_user, egress,
                                                     interest_cache, conn)
                if not alive:
                    break
        finally:
            try:
                await egress.flush()
            finally:
                for item in items[idx:]:
                    item.release()
        return alive

    async def _route_chunk(self, sender_id, chunk: FrameChunk, mode: int,
                           is_user: bool, egress: EgressBatch,
                           interest_cache: dict, conn=None) -> bool:
        """Cut-through one chunk: plan → egress views → residual scalar →
        resume. The chunk's permit is released here (leases keep it alive
        under pending zero-copy flushes)."""
        offs = np.asarray(chunk.offs, np.int64)
        lens = np.asarray(chunk.lens, np.int64)
        buf = chunk.buf
        n = len(offs)
        pos = chunk._pos  # 0 unless someone partially took frames
        planner = self.planner
        try:
            while pos < n:
                # Revalidate the snapshot before EVERY plan call: the
                # egress awaits below can park this task while another
                # task mutates routing state (a subscribe on a different
                # connection), and the scalar path's per-message
                # interest_version check would see that mutation — so
                # must we. Two int compares when nothing changed.
                if not self._refresh():
                    return await self._chunk_scalar_from(
                        sender_id, chunk, offs, lens, pos, is_user,
                        egress, interest_cache, conn)
                t0 = time.perf_counter()
                pump = self._get_pump()
                if pump is not None:
                    # fused path (ISSUE 15): plan + native linked send
                    # SQEs in ONE C call; escalated (peer, frame) pairs
                    # come back and ride the normal _send_plan below
                    consumed, stop, peers, frames, pumped = \
                        pump.plan_and_pump(self, chunk, buf, offs, lens,
                                           pos, mode)
                else:
                    pumped = 0
                    consumed, stop, peers, frames = planner.plan(
                        buf, offs, lens, pos, mode)
                # one perf_counter pair + locked add per CHUNK-level plan
                # call — the latency-attribution seam /metrics exposes as
                # cdn_native_seconds{kernel="route_plan"}
                metrics_mod.NATIVE_PLAN_SECONDS.inc(
                    time.perf_counter() - t0)
                if consumed:
                    metrics_mod.ROUTE_BATCH_SIZE.observe(consumed)
                    if pumped:
                        metrics_mod.ROUTE_PUMP_FRAMES.inc(consumed)
                    else:
                        metrics_mod.ROUTE_CUTTHROUGH_FRAMES.inc(consumed)
                    self._frames_since_rebuild += consumed
                    # per-class ingress accounting off the plan's class
                    # array (pumped runs count their own dir=out in C;
                    # residual pairs count in _send_plan below)
                    fc = (pump.np_.frame_classes if pump is not None
                          else planner.frame_classes)
                    _inc_class_counts(fc[pos:pos + consumed],
                                      lens[pos:pos + consumed],
                                      metrics_mod.CLASS_FRAMES_IN,
                                      metrics_mod.CLASS_BYTES_IN)
                    self._ledger_ingress_fold(
                        fc, pos, consumed, buf, offs, lens,
                        None if is_user else sender_id)
                    # durable retention seam (ISSUE 14): stamp the consumed
                    # broadcasts in the same synchronous region as the plan
                    # (before the first egress await), so a SubscribeFrom
                    # landing mid-send sees exactly the planned frames in
                    # its replay snapshot — no gap, no dup
                    durable = self.broker.durable
                    if durable is not None and durable.topics:
                        durable.retain_from_chunk(buf, offs, lens, pos,
                                                  consumed)
                    await self._send_plan(chunk, offs, lens, peers, frames,
                                          fc)
                pos += consumed
                if stop == routeplan.STOP_END:
                    break
                if stop == routeplan.STOP_CAPACITY:
                    if consumed == 0:  # cannot make progress (can't
                        return await self._chunk_scalar_from(  # happen:
                            sender_id, chunk, offs, lens, pos,  # cap >=
                            is_user, egress, interest_cache, conn)
                    continue
                # STOP_RESIDUAL: the frame at `pos` is a control frame or
                # malformed — scalar semantics, then re-plan (the control
                # frame bumps interest_version, so the next plan call
                # rebuilds the snapshot first)
                metrics_mod.ROUTE_RESIDUAL_FRAMES.inc()
                o, ln = int(offs[pos]), int(lens[pos])
                try:
                    message = deserialize(memoryview(buf)[o:o + ln])
                except Error:
                    self._log_malformed(sender_id, is_user, conn)
                    return False  # malformed ⇒ disconnect/drop link
                if isinstance(message, (Direct, Broadcast)):
                    # TRACED hot frames stop the plan on the kind-tag flag
                    # bit (route_plan.cpp) and take this instrumented
                    # scalar path — the raw frame (flag + trace block
                    # intact) is forwarded verbatim so receivers emit the
                    # delivery span; the rest of the chunk stays batched.
                    # Untraced well-formed hot frames never stop the plan
                    # (this branch is then defensive only).
                    frame = Bytes(buf[o:o + ln])
                    alive = self._route_one_scalar(sender_id, message,
                                                   frame, is_user, egress,
                                                   interest_cache, conn)
                    frame.release()
                else:
                    alive = self._route_one_scalar(sender_id, message,
                                                   None, is_user, egress,
                                                   interest_cache, conn)
                if not alive:
                    return False
                # A residual hot frame (traced, or the defensive case)
                # landed in the egress ACCUMULATOR; the resumed plan's
                # _send_plan enqueues straight to the writers, so flush
                # now or the rest of the chunk overtakes it on the wire.
                # No-op for control frames (empty accumulator).
                await egress.flush()
                pos += 1  # loop top revalidates the (likely bumped) snapshot
        finally:
            chunk.release()
        return True

    async def _chunk_scalar(self, sender_id, chunk: FrameChunk,
                            is_user: bool, egress: EgressBatch,
                            interest_cache: dict, conn=None) -> bool:
        offs = np.asarray(chunk.offs, np.int64)
        lens = np.asarray(chunk.lens, np.int64)
        try:
            return await self._chunk_scalar_from(
                sender_id, chunk, offs, lens, chunk._pos, is_user, egress,
                interest_cache, conn)
        finally:
            chunk.release()

    async def _chunk_scalar_from(self, sender_id, chunk: FrameChunk,
                                 offs, lens, pos: int, is_user: bool,
                                 egress: EgressBatch,
                                 interest_cache: dict, conn=None) -> bool:
        """Scalar fallback over a chunk's remaining frames (snapshot build
        failed). Mirrors the handlers.py loop bodies exactly."""
        buf = chunk.buf
        for i in range(pos, len(offs)):
            metrics_mod.ROUTE_SCALAR_FRAMES.inc()
            o, ln = int(offs[i]), int(lens[i])
            try:
                message = deserialize(memoryview(buf)[o:o + ln])
            except Error:
                self._log_malformed(sender_id, is_user, conn)
                return False
            if isinstance(message, (Direct, Broadcast)):
                frame = Bytes(buf[o:o + ln])
                ok = self._route_one_scalar(sender_id, message, frame,
                                            is_user, egress,
                                            interest_cache, conn)
                frame.release()
            else:
                ok = self._route_one_scalar(sender_id, message, None,
                                            is_user, egress,
                                            interest_cache, conn)
            if not ok:
                return False
        return True
