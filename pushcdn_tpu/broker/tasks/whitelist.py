"""Whitelist enforcement task.

Capability parity with cdn-broker/src/tasks/broker/whitelist.rs:19-44:
every whitelist interval (60 s default) re-check every connected user
against the discovery whitelist and kick anyone who has been removed.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


async def whitelist_once(broker: "Broker") -> None:
    for public_key in list(broker.connections.users.keys()):
        if not await broker.discovery.check_whitelist(public_key):
            logger.info("user %s no longer whitelisted; kicking",
                        mnemonic(public_key))
            broker.connections.remove_user(public_key,
                                           reason="removed from whitelist")
    broker.update_metrics()


async def run_whitelist_task(broker: "Broker") -> None:
    while True:
        await asyncio.sleep(broker.config.whitelist_interval_s)
        await whitelist_once(broker)
