"""Send helpers. **Failure ⇒ removal**: a failed send is the fault
detector — the peer is removed and its tasks aborted (parity
cdn-broker/src/tasks/broker/sender.rs:17-58, tasks/user/sender.rs:16-32;
SURVEY.md §5 "failure *is* an I/O error").

All senders take refcounted :class:`Bytes` frames and clone per recipient —
fan-out shares one payload buffer (Arc-clone parity, handler.rs hot path).
"""

from __future__ import annotations

import logging
import time
from typing import TYPE_CHECKING, Iterable, List, Optional

from pushcdn_tpu import native as native_mod
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


def _pumped(connection) -> str:
    """Failure-log tag for peers the fused pump (transport/pump.py) had
    natively engaged: the removal an operator sees here is the Python
    rediscovery of an error the pump already counted
    (``cdn_pump_escalations{reason="peer_error"}``) — the tag makes the
    two log/metric trails correlate."""
    stream = getattr(connection, "_stream", None)
    if getattr(stream, "_pump_binding", None) is not None:
        return " [natively pumped peer]"
    return ""

# pre-encode shape bounds: the fast path covers fan-out batches of small
# frames (the hot regime); anything bigger rides the writer's own
# coalescer, which chunks large flushes per timeout window
_PRE_ENCODE_MAX_FRAME = 64 * 1024
_PRE_ENCODE_MAX_TOTAL = 1 << 20


def pre_encode_frames(raws) -> Optional[bytearray]:
    """Length-delimit a batch of small ``bytes`` frames into ONE owned
    buffer via the native batch encoder (one C call, one copy — the same
    copy count as the writer-side coalescer, moved off the writer task so
    the flush is verbatim and the frames' pool permits release at encode
    time). None when the native library is unavailable or the batch
    doesn't fit the fast-path shape (callers fall back to
    ``send_raw_many``)."""
    encoder = native_mod.shared_encoder()
    if encoder is None or len(raws) < 2:
        return None
    total = 0
    payloads = []
    for r in raws:
        data = r.data if isinstance(r, Bytes) else r
        if type(data) is not bytes or len(data) > _PRE_ENCODE_MAX_FRAME:
            return None
        total += len(data) + 4
        if total > _PRE_ENCODE_MAX_TOTAL:
            return None
        payloads.append(data)
    t0 = time.perf_counter()
    out = encoder.encode_detached(payloads)
    # batch-level native-seam accounting: one perf_counter pair per
    # fan-out batch (cdn_native_seconds{kernel="egress_encode"})
    metrics_mod.NATIVE_EGRESS_SECONDS.inc(time.perf_counter() - t0)
    return out


async def try_send_to_user(broker: "Broker", public_key: bytes,
                           raw: Bytes, cls: int = 2) -> bool:
    """Queue ``raw`` (one clone) to a local user; remove the user on
    failure. The clone is released by the writer task after the frame hits
    the stream, or by us on failure. ``cls`` is the flow class counted at
    the writer (default ``live`` — this is a data-frame path)."""
    connection = broker.connections.get_user_connection(public_key)
    if connection is None:
        return False
    clone = raw.clone()
    try:
        await connection.send_raw(clone, cls=cls)
        return True
    except Exception as exc:
        clone.release()
        logger.info("send to user %s failed (%r)%s; removing",
                    mnemonic(public_key), exc, _pumped(connection))
        broker.connections.remove_user(public_key, reason="send failed")
        broker.update_metrics()
        return False


def try_send_frames_to_user_nowait(broker: "Broker", public_key: bytes,
                                   raws: Iterable[Bytes]) -> int:
    """Queue a whole batch of frames to one user as ONE send queue entry
    (single connection lookup, single writer wakeup — the device-plane
    egress delivers per-user groups). Returns the number queued; a failure
    removes the user."""
    connection = broker.connections.get_user_connection(public_key)
    if connection is None:
        return 0
    raws = list(raws)
    if not raws:
        return 0
    # Pre-encoded fast path: the whole batch becomes one verbatim writer
    # flush, and the borrowed frames need no clones at all (the encode
    # copies; the caller keeps ownership of the originals).
    encoded = pre_encode_frames(raws)
    try:
        if encoded is not None:
            # nframes carries the batch's frame count into the writer's
            # class accounting (an encoded stream is otherwise opaque)
            connection.send_encoded_nowait(encoded, nframes=len(raws))
        else:
            # the connection owns the clones from here (released on
            # failure too)
            connection.send_raw_many_nowait([raw.clone() for raw in raws])
        return len(raws)
    except Exception as exc:
        logger.info("nowait send to user %s failed (%r)%s; removing",
                    mnemonic(public_key), exc, _pumped(connection))
        broker.connections.remove_user(public_key, reason="send failed")
        broker.update_metrics()
        return 0


def try_send_encoded_to_user_nowait(broker: "Broker", public_key: bytes,
                                    data, owner=None,
                                    nframes: int = 0) -> bool:
    """Queue a pre-framed egress stream (native.egress_encode output) to
    one user — zero per-frame work here or in the writer; a failure
    removes the user (failure-is-removal, as everywhere). ``owner`` keeps
    a pooled egress buffer alive until the flush completes. ``nframes``
    feeds the writer's class accounting (the stream itself is opaque)."""
    connection = broker.connections.get_user_connection(public_key)
    if connection is None:
        return False
    try:
        connection.send_encoded_nowait(data, owner, nframes=nframes)
        return True
    except Exception as exc:
        logger.info("encoded send to user %s failed (%r)%s; removing",
                    mnemonic(public_key), exc, _pumped(connection))
        broker.connections.remove_user(public_key, reason="send failed")
        broker.update_metrics()
        return False


def egress_streams(broker: "Broker", slots, streams) -> int:
    """Deliver one step's native egress (:class:`native.EgressStreams`):
    one pre-framed stream handoff per user with deliveries. Returns the
    number of messages queued."""
    routed = 0
    for slot in streams.users:
        key = slots.key_of(int(slot))
        if key is None:  # released mid-step: user is gone, drop
            continue
        if try_send_encoded_to_user_nowait(broker, key, streams.stream(slot),
                                           owner=streams,
                                           nframes=int(streams.msgs[slot])):
            routed += int(streams.msgs[slot])
    return routed


def egress_delivery_rows(broker: "Broker", slots, users, frame_idx,
                         frame_of) -> int:
    """Shared device-plane egress walk: deliver a (users, frame_idx)
    nonzero listing grouped per user (np.nonzero is row-major, so each
    user's frames are contiguous — one connection lookup per user).
    ``frame_of(f)`` materializes/caches the frame's Bytes; ``slots`` maps
    user slot → public key. Returns the number queued."""
    routed = 0
    start = 0
    n = len(users)
    while start < n:
        u = users[start]
        end = start
        while end < n and users[end] == u:
            end += 1
        key = slots.key_of(int(u))
        if key is not None:  # released mid-step: drop (user is gone)
            routed += try_send_frames_to_user_nowait(
                broker, key, [frame_of(int(f)) for f in frame_idx[start:end]])
        start = end
    return routed


async def try_send_to_broker(broker: "Broker", identifier: str,
                             raw: Bytes) -> bool:
    connection = broker.connections.get_broker_connection(identifier)
    if connection is None:
        return False
    clone = raw.clone()
    try:
        await connection.send_raw(clone)
        # control-plane mesh frames (topic/ledger sync) ride this path
        # rather than the routed egress batches — count them into the
        # per-link conservation table with the same wire-byte rule the
        # receiving end uses, or every mesh link reads recv > sent
        ledger_mod.note_link_sent(identifier, flowclass.frame_class(raw.data))
        return True
    except Exception as exc:
        clone.release()
        logger.info("send to broker %s failed (%r); removing", identifier, exc)
        broker.connections.remove_broker(identifier, reason="send failed")
        broker.update_metrics()
        return False


async def try_send_to_brokers(broker: "Broker", identifiers: Iterable[str],
                              raw: Bytes) -> int:
    """Fan a frame out to many peers (sender.rs try_send_to_brokers)."""
    sent = 0
    for ident in list(identifiers):
        if await try_send_to_broker(broker, ident, raw):
            sent += 1
    return sent
