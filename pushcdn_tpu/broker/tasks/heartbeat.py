"""Heartbeat + mesh formation.

Capability parity with cdn-broker/src/tasks/broker/heartbeat.rs:28-109:
every heartbeat interval (10 s default), publish our user count to
discovery with the membership TTL (60 s), fetch the peer set, and dial any
live peer we aren't connected to — but only when ``peer ≥ self`` in the
identifier total order, so each unordered pair is dialed from exactly one
side (heartbeat.rs:69-73). The candidate list is shuffled to avoid
lockstep connection storms (heartbeat.rs:77).

The mesh self-heals through this task: a dead link is removed by the
senders/receive loops, and the next tick re-dials (SURVEY.md §5).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import TYPE_CHECKING

from pushcdn_tpu.broker.tasks.listeners import handle_broker_connection

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


async def _dial(broker: "Broker", peer) -> None:
    peer_id = str(peer)
    try:
        connection = await broker.run_def.broker_def.protocol.connect(
            peer.private_advertise_endpoint, limiter=broker.limiter)
        await handle_broker_connection(broker, connection, outbound=True)
    except Exception as exc:
        logger.info("dial to broker %s failed: %r", peer_id, exc)
    finally:
        broker.seen_dialing.discard(peer_id)


async def heartbeat_once(broker: "Broker") -> None:
    if broker.draining:
        # elastic drain (ISSUE 12): a draining broker must leave placement
        # rotation immediately, not age out after the membership TTL — and
        # a heartbeat here would re-insert the row deregister just removed
        try:
            await broker.discovery.deregister()
        except Exception as exc:
            broker.note_discovery_probe(False, f"deregister failed: {exc!r}")
            raise
        broker.note_discovery_probe(True, "draining: deregistered")
        return
    # every heartbeat IS a discovery-store probe: report the outcome to
    # the readiness plane so /readyz's cached-TTL check stays fresh for
    # free in steady state (ISSUE 5)
    try:
        # num_users_global: on a sharded broker, shard 0 heartbeats for the
        # whole box (the marshal's load balancing must see every worker's
        # users, not just shard 0's)
        await broker.discovery.perform_heartbeat(
            broker.connections.num_users_global,
            broker.config.membership_ttl_s)
    except Exception as exc:
        broker.note_discovery_probe(False, f"heartbeat failed: {exc!r}")
        raise
    broker.note_discovery_probe(True, "heartbeat ok")
    if not broker.config.form_mesh:
        # device-mesh-only inter-broker plane: skip host dialing only while
        # the mesh plane actually covers ALL inter-broker traffic. Fail open
        # to host links when (a) there is no broker-covering plane, (b) the
        # plane disabled itself, or (c) overflow traffic exists that the
        # plane can't carry (oversized frames, out-of-range topics,
        # unmirrored users, out-of-group recipients) — that traffic rides
        # host links, so without them it would be silently lost.
        plane = broker.device_plane
        covers = plane is not None and getattr(plane, "covers_brokers", False)
        if covers and not plane.disabled and not plane.overflow_seen:
            return
        if plane is not None and (plane.disabled or plane.overflow_seen):
            state = "disabled" if plane.disabled else "has overflow traffic"
            if getattr(broker, "_fail_open_logged", None) != state:
                broker._fail_open_logged = state  # log each state change
                logger.warning(                   # once, not every tick
                    "device plane %s; enabling host mesh dialing", state)
    peers = await broker.discovery.get_other_brokers()
    broker.last_peer_count = len(peers)  # the /readyz solo-vs-partitioned signal
    me = str(broker.identity)
    candidates = [
        p for p in peers
        if str(p) >= me                                    # pairwise dedup
        and not broker.connections.has_broker(str(p))      # not connected
        and str(p) not in broker.seen_dialing              # not mid-dial
    ]
    random.shuffle(candidates)  # avoid lockstep (heartbeat.rs:77)
    for peer in candidates:
        broker.seen_dialing.add(str(peer))
        asyncio.create_task(_dial(broker, peer))


async def run_heartbeat_task(broker: "Broker") -> None:
    while True:
        await heartbeat_once(broker)
        # sleep until the next tick — or earlier, if the device plane sees
        # overflow traffic and kicks us to form host links promptly
        try:
            async with asyncio.timeout(broker.config.heartbeat_interval_s):
                await broker.host_links_kick.wait()
            broker.host_links_kick.clear()
        except asyncio.TimeoutError:
            pass
