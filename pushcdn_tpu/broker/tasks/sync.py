"""State sync / anti-entropy.

Capability parity with cdn-broker/src/tasks/broker/sync.rs:24-145: every
sync interval (10 s default) broadcast ``diff()``-based partial user + topic
syncs to all peers; on a new peer link, send **full** syncs. The CRDT delta
is serialized by the versioned-map codec and nested inside the
``UserSync``/``TopicSync`` message envelope (the reference nests rkyv inside
capnp the same way).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Optional

from pushcdn_tpu.broker.tasks.senders import try_send_to_broker, try_send_to_brokers
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import LedgerSync, TopicSync, UserSync, serialize

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


def _frame(message) -> Bytes:
    """Serialize a sync message into an unpooled Bytes frame (control-plane
    traffic doesn't draw from the user byte pool)."""
    return Bytes(serialize(message))


async def partial_user_sync(broker: "Broker") -> None:
    payload = broker.connections.get_partial_user_sync()
    if payload is None:
        return
    raw = _frame(UserSync(payload=payload))
    await try_send_to_brokers(broker, broker.connections.all_broker_identifiers(), raw)
    raw.release()


async def partial_topic_sync(broker: "Broker") -> None:
    payload = broker.connections.get_partial_topic_sync()
    if payload is None:
        return
    raw = _frame(TopicSync(payload=payload))
    await try_send_to_brokers(broker, broker.connections.all_broker_identifiers(), raw)
    raw.release()


async def full_user_sync(broker: "Broker", peer: str) -> None:
    """Full DirectMap snapshot to one (new) peer (sync.rs:49-104)."""
    raw = _frame(UserSync(payload=broker.connections.get_full_user_sync()))
    await try_send_to_broker(broker, peer, raw)
    raw.release()


async def full_topic_sync(broker: "Broker", peer: str) -> None:
    raw = _frame(TopicSync(payload=broker.connections.get_full_topic_sync()))
    await try_send_to_broker(broker, peer, raw)
    raw.release()


async def ledger_sync(broker: "Broker") -> None:
    """Broadcast this process's conservation balance sheet (ISSUE 20):
    monotone per-link sent/received counters + fate totals, as an opaque
    JSON ``LedgerSync``. Snapshot-sized and interval-paced — no
    per-frame wire overhead."""
    if not ledger_mod.LEDGER.enabled:
        return
    import json
    sheet = ledger_mod.LEDGER.sheet(broker.connections.identity)
    raw = _frame(LedgerSync(payload=json.dumps(sheet).encode()))
    await try_send_to_brokers(broker, broker.connections.all_broker_identifiers(), raw)
    raw.release()


async def run_sync_task(broker: "Broker") -> None:
    """Periodic partial syncs to every peer (sync.rs:129-145)."""
    while True:
        await asyncio.sleep(broker.config.sync_interval_s)
        await partial_user_sync(broker)
        await partial_topic_sync(broker)
        await ledger_sync(broker)
