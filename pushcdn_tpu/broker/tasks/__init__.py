"""The broker task plane (parity cdn-broker/src/tasks/): listeners and
receive loops (handlers), routing core + senders, and the periodic
heartbeat / sync / whitelist tasks."""
