"""Accept loops + connection handlers for users and peer brokers.

Capability parity with cdn-broker/src/tasks/user/listener.rs:22-46,
tasks/user/handler.rs:26-103, tasks/broker/listener.rs:22-46 and
tasks/broker/handler.rs:31-117: accept cheaply, finalize + authenticate in
a spawned per-connection task (so one slow handshake can't stall the accept
loop), register, spawn the receive loop, and — for new peer brokers — push
a **full** topic + user sync (handler.rs:98-117).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from pushcdn_tpu.broker.tasks import sync as sync_task
from pushcdn_tpu.broker.tasks.handlers import broker_receive_loop, user_receive_loop
from pushcdn_tpu.proto.auth import broker as broker_auth
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import AuthenticateResponse
from pushcdn_tpu.proto.util import AbortOnDropHandle, mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


# ---------------------------------------------------------------------------
# users (public side)
# ---------------------------------------------------------------------------

async def run_user_listener_task(broker: "Broker") -> None:
    while True:
        unfinalized = await broker.user_listener.accept()
        asyncio.create_task(handle_user_connection(broker, unfinalized))


async def handle_user_connection(broker: "Broker", unfinalized) -> None:
    """Finalize → permit auth (5 s) → topic prune → register + spawn receive
    loop (user/handler.rs:26-103)."""
    connection = None
    try:
        connection = await unfinalized.finalize(broker.limiter)
        # admission control (ISSUE 7): an over-budget connection is shed
        # BEFORE the auth handshake — no signature verify or discovery
        # round-trip spent on a connection we won't keep. The typed
        # refusal (permit=0 + reason) is what the client library surfaces
        # as Error(AUTHENTICATION) and re-load-balances on.
        adm = broker.admission
        shed = adm.admit_user() if adm is not None else None
        if shed is not None:
            connection.flightrec.record("load-shed", shed, abnormal=True)
            try:
                await connection.send_message(
                    AuthenticateResponse(permit=0, context=shed),
                    flush=True)
            except Exception:
                pass
            connection.close()
            return
        async with asyncio.timeout(broker.config.auth_timeout_s):
            public_key, topics = await broker_auth.verify_user(
                connection, broker.discovery, broker.identity)
        pruned, had_invalid = broker.run_def.topics.prune(topics)
        if had_invalid:
            # invalid topics at the handshake ⇒ reject the connection
            connection.close()
            return

        connection.flightrec.label += f" user={mnemonic(public_key)}"
        connection.flightrec.record("auth-ok", mnemonic(public_key))
        loop_task = asyncio.create_task(
            user_receive_loop(broker, public_key, connection))
        broker.connections.add_user(public_key, connection, pruned,
                                    AbortOnDropHandle(loop_task))
        broker.update_metrics()

        if broker.run_def.strong_consistency:
            # push partial syncs immediately so peers learn about this user
            # now rather than at the next 10 s tick (user/handler.rs:79-90,
            # the `strong-consistency` feature — broker default)
            await sync_task.partial_user_sync(broker)
            await sync_task.partial_topic_sync(broker)
    except (Error, asyncio.TimeoutError) as exc:
        logger.info("user connection failed auth: %r", exc)
        if connection is not None:
            # routine under connection storms: recorded (visible at
            # /debug/flightrec while the handle lives) but not dumped
            connection.flightrec.record("auth-fail", repr(exc))
            connection.close()
    except asyncio.CancelledError:
        if connection is not None:
            connection.close()
        raise


# ---------------------------------------------------------------------------
# brokers (private side)
# ---------------------------------------------------------------------------

async def run_broker_listener_task(broker: "Broker") -> None:
    while True:
        unfinalized = await broker.broker_listener.accept()
        asyncio.create_task(
            handle_broker_connection(broker, unfinalized, outbound=False))


async def handle_broker_connection(broker: "Broker", connection_or_unfinalized,
                                   outbound: bool) -> None:
    """Mutual auth (direction-ordered), register, spawn receive loop, then
    full sync to the new peer (broker/handler.rs:31-117).

    ``outbound=True``: we dialed (already-finalized connection);
    ``outbound=False``: accepted (unfinalized).
    """
    connection = None
    try:
        if outbound:
            connection = connection_or_unfinalized
        else:
            connection = await connection_or_unfinalized.finalize(broker.limiter)
            # broker-tier budget (inbound only — a link WE dialed was a
            # deliberate mesh decision): over budget, the link is closed
            # pre-auth; the dialer's next heartbeat retries
            adm = broker.admission
            shed = adm.admit_broker() if adm is not None else None
            if shed is not None:
                connection.flightrec.record("load-shed", shed,
                                            abnormal=True)
                logger.warning("inbound broker link refused: %s", shed)
                connection.close()
                return
        async with asyncio.timeout(broker.config.auth_timeout_s):
            if outbound:
                peer = await broker_auth.authenticate_as_dialer(
                    connection, broker.run_def.broker_def.scheme,
                    broker.config.keypair, broker.identity)
            else:
                peer = await broker_auth.authenticate_as_listener(
                    connection, broker.run_def.broker_def.scheme,
                    broker.config.keypair, broker.identity)
        peer_id = str(peer)
        if peer_id == broker.connections.identity:
            connection.close()
            return

        connection.flightrec.label += f" broker={peer_id}"
        connection.flightrec.record("auth-ok", peer_id)
        loop_task = asyncio.create_task(
            broker_receive_loop(broker, peer_id, connection))
        broker.connections.add_broker(peer_id, connection,
                                      AbortOnDropHandle(loop_task))
        broker.update_metrics()
        logger.info("broker link %s established (%s)",
                    peer_id, "outbound" if outbound else "inbound")

        # Initial FULL sync so the newcomer converges instantly
        # (broker/handler.rs:98-117).
        await sync_task.full_topic_sync(broker, peer_id)
        await sync_task.full_user_sync(broker, peer_id)
    except (Error, asyncio.TimeoutError) as exc:
        logger.info("broker link failed auth: %r", exc)
        if connection is not None:
            connection.close()
    except asyncio.CancelledError:
        if connection is not None:
            connection.close()
        raise
