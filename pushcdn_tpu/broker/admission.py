"""Admission control & overload shedding (ISSUE 7).

The incremental route plane makes the control path O(delta), but a
million-user box still needs a policy for the work it should NOT accept:
past the configured budgets the broker REFUSES cheaply instead of letting
the event loop collapse under connection or subscribe storms. Three tiers:

- **per-tier connection budgets** — ``PUSHCDN_MAX_CONNS_USER`` /
  ``PUSHCDN_MAX_CONNS_BROKER`` cap live connections per worker process
  (0 = unlimited, the default). A user over budget is refused BEFORE the
  auth handshake (no BLS verify, no discovery round-trip spent on a
  connection we won't keep) with a typed ``AuthenticateResponse(permit=0,
  context="shed: ...")`` — the client library surfaces it as
  ``Error(AUTHENTICATION)`` and re-load-balances through the marshal. An
  over-budget peer broker link is closed (the dialer's heartbeat retries).
- **subscribe-rate limiting** — a per-connection token bucket
  (``PUSHCDN_SUBSCRIBE_RATE`` tokens/s, burst ``PUSHCDN_SUBSCRIBE_BURST``)
  over Subscribe/Unsubscribe frames. An over-rate mutation is DROPPED
  (not applied, sender stays connected) and the client is told with a
  typed shed notice riding the normal egress path — never a silent drop;
  the client library raises ``Error(SHED)``.
- **surfacing** — every shed increments ``cdn_route_shed_total{tier=...}``,
  records a ``load-shed`` flight-recorder event (visible at
  ``/debug/flightrec`` and in abnormal-teardown dumps), and flips the
  broker's ``/readyz`` ``admission`` check false for
  ``PUSHCDN_SHED_READY_S`` seconds (default 5) so the load balancer
  steers new work away while the box recovers. Degrade, never collapse.
"""

from __future__ import annotations

import logging
import os
import time
from typing import TYPE_CHECKING, Optional, Tuple

from pushcdn_tpu.proto import flightrec
from pushcdn_tpu.proto import flowclass
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.limiter import Bytes
from pushcdn_tpu.proto.message import AuthenticateResponse, serialize

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", name, raw)
        return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


# pre-serialized typed shed notice for the hot (subscribe) tier — permit=0
# marks refusal, context says why; the client maps it to Error(SHED)
_SUBSCRIBE_SHED_CONTEXT = ("shed: subscribe rate exceeded "
                           "(PUSHCDN_SUBSCRIBE_RATE)")
_SUBSCRIBE_SHED_FRAME = serialize(
    AuthenticateResponse(permit=0, context=_SUBSCRIBE_SHED_CONTEXT))


class AdmissionControl:
    """Per-broker admission policy. Synchronous and allocation-free on the
    allow path (one monotonic read + float math per rate check)."""

    __slots__ = ("broker", "max_user_conns", "max_broker_conns",
                 "subscribe_rate", "subscribe_burst", "ready_window_s",
                 "last_shed", "shed_counts")

    def __init__(self, broker: "Broker"):
        self.broker = broker
        self.max_user_conns = _env_int("PUSHCDN_MAX_CONNS_USER", 0)
        self.max_broker_conns = _env_int("PUSHCDN_MAX_CONNS_BROKER", 0)
        self.subscribe_rate = _env_float("PUSHCDN_SUBSCRIBE_RATE", 0.0)
        burst_default = max(8.0, 4 * self.subscribe_rate)
        self.subscribe_burst = _env_float("PUSHCDN_SUBSCRIBE_BURST",
                                          burst_default)
        self.ready_window_s = _env_float("PUSHCDN_SHED_READY_S", 5.0)
        self.last_shed: dict = {}    # tier -> monotonic ts of last shed
        self.shed_counts: dict = {}  # tier -> total (topology summary)

    @property
    def enabled(self) -> bool:
        return (self.max_user_conns > 0 or self.max_broker_conns > 0
                or self.subscribe_rate > 0)

    # -- connection budgets ---------------------------------------------------

    def admit_user(self) -> Optional[str]:
        """None = admit; else the shed reason (typed back to the client).
        Budgets are per worker process — a ``--shards N`` box multiplies
        them by N."""
        if self.max_user_conns <= 0:
            return None
        if self.broker.connections.num_users < self.max_user_conns:
            return None
        # retry-after rides the context as a typed hint (ISSUE 12): the
        # readiness window is exactly how long the balancer steers away,
        # so it is the honest earliest-useful-retry estimate
        reason = (f"shed: user connection budget {self.max_user_conns} "
                  f"reached (PUSHCDN_MAX_CONNS_USER); "
                  f"retry-after={self.ready_window_s:g}")
        self._note_shed("user_conn", reason, None,
                        metrics_mod.ROUTE_SHED_USER_CONN)
        return reason

    def admit_broker(self) -> Optional[str]:
        if self.max_broker_conns <= 0:
            return None
        if self.broker.connections.num_brokers < self.max_broker_conns:
            return None
        reason = (f"shed: broker link budget {self.max_broker_conns} "
                  f"reached (PUSHCDN_MAX_CONNS_BROKER); "
                  f"retry-after={self.ready_window_s:g}")
        self._note_shed("broker_conn", reason, None,
                        metrics_mod.ROUTE_SHED_BROKER_CONN)
        return reason

    # -- subscribe-rate token bucket -----------------------------------------

    def allow_subscribe(self, conn) -> bool:
        """One token per Subscribe/Unsubscribe frame from ``conn``; False
        means drop-and-notify (the caller queues the typed shed notice)."""
        rate = self.subscribe_rate
        if rate <= 0 or conn is None:
            return True
        now = time.monotonic()
        bucket = getattr(conn, "_sub_bucket", None)
        if bucket is None:
            conn._sub_bucket = [self.subscribe_burst - 1.0, now]
            return True
        tokens = min(self.subscribe_burst,
                     bucket[0] + (now - bucket[1]) * rate)
        bucket[1] = now
        if tokens < 1.0:
            bucket[0] = tokens
            return False
        bucket[0] = tokens - 1.0
        return True

    def shed_subscribe(self, sender_key, conn, egress) -> None:
        """Drop an over-rate subscription mutation: count it, arm the
        recorder, and queue the typed notice back to the sender through
        the normal egress path (ordered with its other deliveries — a
        shed is never a silent drop)."""
        self._note_shed("subscribe", _SUBSCRIBE_SHED_CONTEXT, conn,
                        metrics_mod.ROUTE_SHED_SUBSCRIBE)
        # the shed mutation frame's terminal fate (control class)
        ledger_mod.record_fate("dropped", "admission_shed",
                               flowclass.CONTROL)
        if egress is not None and sender_key is not None:
            raw = Bytes(_SUBSCRIBE_SHED_FRAME)
            try:
                egress.to_user(sender_key, raw)
            finally:
                raw.release()

    # -- surfacing ------------------------------------------------------------

    def _note_shed(self, tier: str, detail: str, conn, counter) -> None:
        counter.inc()
        self.last_shed[tier] = time.monotonic()
        self.shed_counts[tier] = self.shed_counts.get(tier, 0) + 1
        rec = getattr(conn, "flightrec", None) if conn is not None \
            else flightrec.task_recorder()
        if rec is not None:
            rec.record("load-shed", detail, abnormal=True)

    def readiness_check(self) -> Tuple[bool, str]:
        """The /readyz ``admission`` check: not ready while shedding is
        recent — the load balancer steers away until the box has served
        ``ready_window_s`` without refusing work."""
        if not self.enabled:
            return True, "admission control disabled (no budgets set)"
        now = time.monotonic()
        recent = sorted(tier for tier, ts in self.last_shed.items()
                        if now - ts < self.ready_window_s)
        if recent:
            return False, f"load shedding active ({', '.join(recent)})"
        return True, "no recent load shed"

    def summary(self) -> dict:
        """Operator-facing state for ``/debug/topology``."""
        now = time.monotonic()
        return {
            "enabled": self.enabled,
            "max_user_conns": self.max_user_conns,
            "max_broker_conns": self.max_broker_conns,
            "subscribe_rate": self.subscribe_rate,
            "subscribe_burst": self.subscribe_burst,
            "shed_counts": dict(self.shed_counts),
            "last_shed_ago_s": {
                tier: round(now - ts, 3)
                for tier, ts in self.last_shed.items()},
        }
