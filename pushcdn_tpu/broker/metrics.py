"""Broker gauges (parity cdn-broker/src/metrics.rs:13-21)."""

from pushcdn_tpu.proto.metrics import Gauge

NUM_USERS_CONNECTED = Gauge("cdn_num_users_connected",
                            "Users currently connected to this broker")
NUM_BROKERS_CONNECTED = Gauge("cdn_num_brokers_connected",
                              "Peer brokers currently connected to this broker")

# device-plane observability (no reference analog — the data plane the
# reference doesn't have): steps run and messages routed on-device,
# updated by broker.update_metrics() from the attached plane's counters
DEVICE_STEPS = Gauge("cdn_device_steps",
                     "Routing steps executed by the attached device plane")
DEVICE_MESSAGES_ROUTED = Gauge(
    "cdn_device_messages_routed",
    "Messages delivered via the device plane's egress")
