"""Broker gauges (parity cdn-broker/src/metrics.rs:13-21)."""

from pushcdn_tpu.proto.metrics import Gauge

NUM_USERS_CONNECTED = Gauge("cdn_num_users_connected",
                            "Users currently connected to this broker")
NUM_BROKERS_CONNECTED = Gauge("cdn_num_brokers_connected",
                              "Peer brokers currently connected to this broker")
