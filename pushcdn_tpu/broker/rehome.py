"""Active user re-homing for elastic drain (ISSUE 12).

A draining broker does not abandon its users to an emergent reconnect
scramble — it plans the migration (the "RPC Considered Harmful" /
DMA-handoff lesson: batch the control work, keep the data plane moving):

1. leave placement rotation NOW: ``discovery.deregister()`` (the
   heartbeat task keeps re-deregistering while ``broker.draining``);
2. for each connected user, pick the least-loaded live peer (every
   issued permit counts toward that peer's load in
   ``get_with_least_connections``, so a mass drain spreads itself
   across the survivors instead of dog-piling one), pre-issue a permit
   bound to that peer, and send a typed :class:`Migrate` frame on the
   ordered egress path — after everything already queued for the user;
3. the client dials the target directly with the pre-issued permit (no
   per-connection marshal round-trip); the target's ``add_user`` claims
   the user in the DirectMap, the strong-consistency partial UserSync
   propagates the eviction row, and THIS broker's merge handler kicks
   the old connection ("user connected elsewhere") — in-flight directs
   chase the user to the new home through the same CRDT row.

The old connection is deliberately NOT closed here (make-before-break):
closing would release our DirectMap claim before the target claims it,
opening a zero-home window for mid-migration directs. Flight-recorder
trail: ``migrate-out`` here at send, ``migrate-in`` on the target at
``add_user``.

Sharded brokers: every worker re-homes its own shard's users (each has
its own discovery client); ``deregister`` is idempotent across workers.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from pushcdn_tpu.proto.auth.marshal import PERMIT_EXPIRY_S
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.message import Migrate
from pushcdn_tpu.proto.util import mnemonic

if TYPE_CHECKING:
    from pushcdn_tpu.broker.broker import Broker

logger = logging.getLogger("pushcdn.broker")


async def rehome_users(broker: "Broker", wait_s: float = 0.0) -> dict:
    """Signal every connected user to migrate; returns a summary dict
    (``users``/``signaled``/``orphaned``/``remaining``). ``wait_s > 0``
    polls for the UserSync evictions to land before reporting
    ``remaining`` (users still attached here)."""
    broker.draining = True
    try:
        await broker.discovery.deregister()
    except Exception as exc:  # a locked store must not abort the drain
        logger.warning("drain deregister failed: %r", exc)

    conns = broker.connections
    keys = list(conns.users.keys())
    signaled = 0
    no_target = False
    for key in keys:
        handle = conns.users.get(key)
        if handle is None:
            continue  # disconnected while we were draining
        try:
            target = await broker.discovery.get_with_least_connections()
        except Error:
            # no live peers: the remaining users stay attached until the
            # process exits, then reconnect through the marshal's backoff
            no_target = True
            break
        try:
            permit = await broker.discovery.issue_permit(
                target, PERMIT_EXPIRY_S, key)
        except Exception as exc:
            logger.warning("drain permit issue failed for %s: %r",
                           mnemonic(key), exc)
            continue
        endpoint = target.public_advertise_endpoint
        try:
            handle.connection.flightrec.record("migrate-out",
                                               f"to {endpoint}")
            await handle.connection.send_message(
                Migrate(target=endpoint, permit=permit), flush=True)
            signaled += 1
        except Exception as exc:
            logger.info("migrate signal to %s failed: %r",
                        mnemonic(key), exc)

    if wait_s > 0:
        deadline = asyncio.get_running_loop().time() + wait_s
        while conns.num_users > 0 \
                and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.05)

    summary = {
        "users": len(keys),
        "signaled": signaled,
        "orphaned": len(keys) - signaled,
        "remaining": conns.num_users,
        "no_target": no_target,
    }
    logger.info("drain re-home: %s", summary)
    return summary
