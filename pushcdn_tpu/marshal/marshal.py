"""Marshal server: accept, verify, hand out a permit, soft-close.

Capability parity with cdn-marshal/src/lib.rs:80-180 + handlers.rs:19-38:
bind the user-facing listener, accept-loop, and for each connection run
``MarshalAuth::verify_user`` under a 5 s timeout then soft-close. The
marshal is stateless (all state lives in discovery) and horizontally
scalable.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Optional

from pushcdn_tpu.proto import health as health_mod
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.auth import marshal as marshal_auth
from pushcdn_tpu.proto.crypto.tls import Certificate, generate_cert_from_ca, load_ca
from pushcdn_tpu.proto.def_ import RunDef
from pushcdn_tpu.proto.error import Error
from pushcdn_tpu.proto.limiter import Limiter

logger = logging.getLogger("pushcdn.marshal")


@dataclass
class MarshalConfig:
    """Parity with the marshal Config (cdn-marshal/src/lib.rs:30-76)."""

    run_def: RunDef
    discovery_endpoint: str
    bind_endpoint: str  # default port 1737 in the reference binary
    metrics_bind_endpoint: Optional[str] = None
    ca_cert_path: Optional[str] = None
    ca_key_path: Optional[str] = None
    global_memory_pool_size: int = 1024 * 1024 * 1024
    auth_timeout_s: float = 5.0
    # /readyz discovery check: re-probe the store at most this often
    discovery_probe_ttl_s: float = 5.0


class Marshal:
    def __init__(self, config: MarshalConfig):
        self.config = config
        self.run_def = config.run_def
        self.discovery = None
        self.listener = None
        self.limiter: Limiter = None
        self.certificate: Optional[Certificate] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._metrics_server = None
        # /readyz state: cached discovery probe (ISSUE 5)
        self._discovery_probe: tuple = (False, "not probed yet")
        self._discovery_probe_at: Optional[float] = None
        # amortize concurrent pairing checks under connection storms
        # (no-op pass-through for schemes without verify_batch)
        from pushcdn_tpu.proto.crypto.batch import BatchVerifier
        self.batch_verifier = BatchVerifier(config.run_def.user_def.scheme)

    @classmethod
    async def new(cls, config: MarshalConfig) -> "Marshal":
        self = cls(config)
        self.discovery = await self.run_def.discovery.new(
            config.discovery_endpoint, identity=None,
            global_permits=self.run_def.global_permits)
        ca_cert, ca_key = load_ca(config.ca_cert_path, config.ca_key_path)
        self.certificate = generate_cert_from_ca(ca_cert, ca_key)
        self.limiter = Limiter(global_pool_bytes=config.global_memory_pool_size)
        if config.metrics_bind_endpoint:
            # the marshal is the process doing BLS verifications, so it
            # exports the pk line-table cache counters alongside the core
            # gauges (the hook only PEEKS at an already-loaded library:
            # for non-BLS schemes the native lib never loads and the
            # gauges stay zero — no compile can fire inside /metrics).
            # Endpoint first, listener second: /readyz is probe-able (and
            # false) before the marshal can actually accept.
            metrics_mod.register_bls_pk_cache_metrics()
            self._metrics_server = await metrics_mod.serve_metrics(
                config.metrics_bind_endpoint)
            health_mod.register_readiness("listener", self._check_listener)
            health_mod.register_readiness("discovery", self._check_discovery)
        self.listener = await self.run_def.user_def.protocol.bind(
            config.bind_endpoint, certificate=self.certificate)
        logger.info("marshal listening on %s", config.bind_endpoint)
        return self

    # -- readiness (ISSUE 5) ------------------------------------------------

    def _check_listener(self):
        if self.listener is None:
            return False, "listener not bound yet"
        return True, f"listening on {self.config.bind_endpoint}"

    async def _check_discovery(self):
        now = time.monotonic()
        if (self._discovery_probe_at is not None
                and now - self._discovery_probe_at
                < self.config.discovery_probe_ttl_s):
            return self._discovery_probe
        try:
            async with asyncio.timeout(2.0):
                brokers = await self.discovery.get_other_brokers()
            self._discovery_probe = (
                len(brokers) > 0,
                f"ok ({len(brokers)} brokers registered)" if brokers
                else "no live brokers to hand users to")
        except Exception as exc:
            self._discovery_probe = (False, f"probe failed: {exc!r}")
        self._discovery_probe_at = now
        return self._discovery_probe

    def begin_drain(self, reason: str = "shutdown") -> None:
        """Flip /readyz to 503 before the listener closes."""
        health_mod.set_draining(reason)

    async def start(self) -> None:
        self._accept_task = asyncio.create_task(self._accept_loop(),
                                                name="marshal-accept")

    async def _accept_loop(self) -> None:
        while True:
            unfinalized = await self.listener.accept()
            asyncio.create_task(self._handle_connection(unfinalized))

    async def _handle_connection(self, unfinalized) -> None:
        """Parity handlers.rs:21-37: finalize → verify (5 s) → soft-close."""
        connection = None
        try:
            connection = await unfinalized.finalize(self.limiter)
            async with asyncio.timeout(self.config.auth_timeout_s):
                public_key, permit = await marshal_auth.verify_user(
                    connection, self.discovery,
                    self.run_def.user_def.scheme,
                    verifier=self.batch_verifier)
            await connection.soft_close()
        except (Error, asyncio.TimeoutError) as exc:
            logger.info("marshal auth failed: %r", exc)
            if connection is not None:
                # routine under storms: recorded, not dumped
                connection.flightrec.record("auth-fail", repr(exc))
                connection.close()
        except asyncio.CancelledError:
            if connection is not None:
                connection.close()
            raise

    async def stop(self) -> None:
        if self._metrics_server is not None:
            self.begin_drain("marshal stop")  # before the listener closes
        if self._accept_task is not None:
            self._accept_task.cancel()
            try:
                await self._accept_task
            except (asyncio.CancelledError, Exception):
                pass
        if self.listener is not None:
            await self.listener.close()
        if self.discovery is not None:
            await self.discovery.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
            self._metrics_server = None
            for name in ("listener", "discovery"):
                health_mod.unregister(name)
            health_mod.clear_draining()
        logger.info("marshal stopped")
