"""The marshal: authentication gateway / load balancer.

Capability parity with the reference's ``cdn-marshal`` crate (SURVEY.md §2c).
"""

from pushcdn_tpu.marshal.marshal import Marshal, MarshalConfig  # noqa: F401
