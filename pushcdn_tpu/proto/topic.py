"""Topic validation / pruning.

Capability parity with the reference's ``Topic`` trait (``prune()``
validation, cdn-proto/src/def.rs:31-51) and the ``TestTopic { Global=0,
DA=1 }`` example (def.rs:23-28). Topics are small ints on the wire
(``u8``, message.rs:26); a ``TopicSpace`` defines which values are valid.

On-device, a topic set is a bitmask over the topic space (one u32/u64 lane
per connection) — see pushcdn_tpu.parallel.frames.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, List, Sequence


class TestTopic(enum.IntEnum):
    """The test topic space (parity def.rs:23-28)."""

    GLOBAL = 0
    DA = 1


@dataclass(frozen=True)
class TopicSpace:
    """The set of valid topic values for a deployment.

    ``prune`` mirrors def.rs:37-51: strip unknown values, dedupe, and report
    whether anything was removed — the broker disconnects users that sent
    *only* invalid topics (tasks/user/handler.rs topic pruning).
    """

    valid: frozenset[int]

    @classmethod
    def from_enum(cls, topic_enum) -> "TopicSpace":
        return cls(frozenset(int(t) for t in topic_enum))

    @classmethod
    def range(cls, n: int) -> "TopicSpace":
        """Topic space 0..n-1 (bitmask-friendly; n ≤ 256)."""
        return cls(frozenset(range(n)))

    def prune(self, topics: Sequence[int]) -> tuple[List[int], bool]:
        """Return (valid-deduped-topics, had_invalid)."""
        seen = set()
        out: List[int] = []
        had_invalid = False
        for t in topics:
            t = int(t)
            if t not in self.valid:
                had_invalid = True
                continue
            if t not in seen:
                seen.add(t)
                out.append(t)
        return out, had_invalid

    def bitmask(self, topics: Iterable[int]) -> int:
        """Pack a topic set into an int bitmask (device representation)."""
        mask = 0
        for t in topics:
            mask |= 1 << int(t)
        return mask


TEST_TOPIC_SPACE = TopicSpace.from_enum(TestTopic)
