"""Topic validation / pruning.

Capability parity with the reference's ``Topic`` trait (``prune()``
validation, cdn-proto/src/def.rs:31-51) and the ``TestTopic { Global=0,
DA=1 }`` example (def.rs:23-28). Topics are small ints on the wire
(``u8``, message.rs:26); a ``TopicSpace`` defines which values are valid.

On-device, a topic set is a bitmask over the topic space (one u32/u64 lane
per connection) — see pushcdn_tpu.parallel.frames.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, List, Sequence


class TestTopic(enum.IntEnum):
    """The test topic space (parity def.rs:23-28)."""

    GLOBAL = 0
    DA = 1


class BoundedTopicMemo:
    """Bounded memo for pure functions of a topic tuple: only
    deployment-sized keys (<=16 topics) are retained — the wire allows
    65535 topics per message, and caching adversarial unique tuples
    would grow a memo into GiBs — and the table clears wholesale at
    4096 entries. One policy, shared by TopicSpace.prune and the device
    planes' TopicMaskCache."""

    __slots__ = ("_memo",)

    MAX_KEY_TOPICS = 16
    MAX_ENTRIES = 4096

    def __init__(self):
        self._memo = {}

    def get(self, topics, compute):
        """Return compute(key) memoized; ``key`` is the tuple form."""
        key = topics if type(topics) is tuple else tuple(topics)
        hit = self._memo.get(key)
        if hit is None:
            hit = compute(key)
            if len(key) <= self.MAX_KEY_TOPICS:
                if len(self._memo) >= self.MAX_ENTRIES:
                    self._memo.clear()
                self._memo[key] = hit
        return hit

    def __len__(self):
        return len(self._memo)


@dataclass(frozen=True)
class TopicSpace:
    """The set of valid topic values for a deployment.

    ``prune`` mirrors def.rs:37-51: strip unknown values, dedupe, and report
    whether anything was removed — the broker disconnects users that sent
    *only* invalid topics (tasks/user/handler.rs topic pruning).
    """

    valid: frozenset[int]

    def __post_init__(self):
        # prune() runs once per received broadcast on every broker, and
        # deployments publish a handful of distinct topic sets — memoize
        object.__setattr__(self, "_prune_memo", BoundedTopicMemo())

    @classmethod
    def from_enum(cls, topic_enum) -> "TopicSpace":
        return cls(frozenset(int(t) for t in topic_enum))

    @classmethod
    def range(cls, n: int) -> "TopicSpace":
        """Topic space 0..n-1 (bitmask-friendly; n ≤ 256)."""
        return cls(frozenset(range(n)))

    def prune(self, topics: Sequence[int]) -> tuple[tuple, bool]:
        """Return (valid-deduped-topics, had_invalid). The topic
        sequence comes back as an immutable TUPLE: results are shared by
        the memo, and a tuple makes that structurally safe."""
        def compute(key):
            seen = set()
            out: List[int] = []
            had_invalid = False
            for t in key:
                t = int(t)
                if t not in self.valid:
                    had_invalid = True
                    continue
                if t not in seen:
                    seen.add(t)
                    out.append(t)
            return tuple(out), had_invalid

        return self._prune_memo.get(topics, compute)

    def bitmask(self, topics: Iterable[int]) -> int:
        """Pack a topic set into an int bitmask (device representation)."""
        mask = 0
        for t in topics:
            mask |= 1 << int(t)
        return mask


class TopicNamespace:
    """Hierarchical names over the integer topic space (durable topics,
    ISSUE 14): ``consensus.view.3`` binds to one wire-level u8 topic, and
    a wildcard pattern (``consensus.view.*``) compiles to the set of
    bound topics it covers.

    Wildcards never reach the route planes: a wildcard subscription is
    resolved here into plain per-topic interest-mask updates, and a
    *watch* keeps it live — every later :meth:`bind` / :meth:`unbind`
    fires the watch callbacks, so the union is maintained incrementally
    (the same shape as RaggedInterest page maintenance). The native
    route-plan kernel and the scalar/sharded twins only ever see the
    compiled mask.

    Pattern grammar: dot-separated segments; ``*`` matches exactly one
    segment, except a FINAL ``*`` which matches one or more trailing
    segments (so ``consensus.view.*`` covers ``consensus.view.3`` and
    ``consensus.view.3.retry``).
    """

    __slots__ = ("space", "_by_name", "_by_topic", "_watches", "_next_watch")

    def __init__(self, space: TopicSpace | None = None):
        self.space = space
        self._by_name: dict[str, int] = {}
        self._by_topic: dict[int, str] = {}
        # watch id -> (pattern segments, on_add, on_remove)
        self._watches: dict[int, tuple] = {}
        self._next_watch = 0

    # -- binding --------------------------------------------------------

    def bind(self, name: str, topic: int | None = None) -> int:
        """Bind ``name`` to ``topic`` (auto-allocates the smallest free
        valid topic when omitted). Idempotent for an identical re-bind;
        a conflicting re-bind raises ``ValueError``. Fires matching
        watches' ``on_add(name, topic)``."""
        if not name or name != name.strip("."):
            raise ValueError(f"invalid topic name {name!r}")
        bound = self._by_name.get(name)
        if bound is not None:
            if topic is not None and topic != bound:
                raise ValueError(
                    f"{name!r} already bound to topic {bound}, not {topic}")
            return bound
        if topic is None:
            universe = (sorted(self.space.valid) if self.space is not None
                        else range(256))
            for cand in universe:
                if cand not in self._by_topic:
                    topic = cand
                    break
            else:
                raise ValueError("topic space exhausted")
        else:
            if self.space is not None and topic not in self.space.valid:
                raise ValueError(f"topic {topic} outside the topic space")
            other = self._by_topic.get(topic)
            if other is not None:
                raise ValueError(f"topic {topic} already bound to {other!r}")
        self._by_name[name] = topic
        self._by_topic[topic] = name
        segs = name.split(".")
        for pat, on_add, _ in list(self._watches.values()):
            if on_add is not None and self._segs_match(pat, segs):
                on_add(name, topic)
        return topic

    def unbind(self, name: str) -> None:
        """Drop a binding; fires matching watches' ``on_remove``."""
        topic = self._by_name.pop(name, None)
        if topic is None:
            return
        del self._by_topic[topic]
        segs = name.split(".")
        for pat, _, on_remove in list(self._watches.values()):
            if on_remove is not None and self._segs_match(pat, segs):
                on_remove(name, topic)

    def topic_of(self, name: str):
        return self._by_name.get(name)

    def name_of(self, topic: int):
        return self._by_topic.get(topic)

    def bindings(self) -> dict[str, int]:
        return dict(self._by_name)

    # -- wildcard compilation -------------------------------------------

    @staticmethod
    def _segs_match(pat: list, segs: list) -> bool:
        np = len(pat)
        if np == 0:
            return False
        tail_glob = pat[-1] == "*"
        if tail_glob:
            if len(segs) < np:           # final * eats one-or-more
                return False
        elif len(segs) != np:
            return False
        for p, s in zip(pat[:-1] if tail_glob else pat, segs):
            if p != "*" and p != s:
                return False
        return True

    def match(self, pattern: str) -> tuple:
        """Compile ``pattern`` to the sorted tuple of bound topics it
        covers right now (a plain name is its own 1-element pattern)."""
        pat = pattern.split(".")
        return tuple(sorted(
            t for n, t in self._by_name.items()
            if self._segs_match(pat, n.split("."))))

    # -- live watches ---------------------------------------------------

    def watch(self, pattern: str, on_add=None, on_remove=None) -> int:
        """Register callbacks fired on every future bind/unbind matching
        ``pattern``; returns a handle for :meth:`unwatch`."""
        self._next_watch += 1
        self._watches[self._next_watch] = (pattern.split("."),
                                           on_add, on_remove)
        return self._next_watch

    def unwatch(self, handle: int) -> None:
        self._watches.pop(handle, None)


TEST_TOPIC_SPACE = TopicSpace.from_enum(TestTopic)
