"""Topic validation / pruning.

Capability parity with the reference's ``Topic`` trait (``prune()``
validation, cdn-proto/src/def.rs:31-51) and the ``TestTopic { Global=0,
DA=1 }`` example (def.rs:23-28). Topics are small ints on the wire
(``u8``, message.rs:26); a ``TopicSpace`` defines which values are valid.

On-device, a topic set is a bitmask over the topic space (one u32/u64 lane
per connection) — see pushcdn_tpu.parallel.frames.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterable, List, Sequence


class TestTopic(enum.IntEnum):
    """The test topic space (parity def.rs:23-28)."""

    GLOBAL = 0
    DA = 1


class BoundedTopicMemo:
    """Bounded memo for pure functions of a topic tuple: only
    deployment-sized keys (<=16 topics) are retained — the wire allows
    65535 topics per message, and caching adversarial unique tuples
    would grow a memo into GiBs — and the table clears wholesale at
    4096 entries. One policy, shared by TopicSpace.prune and the device
    planes' TopicMaskCache."""

    __slots__ = ("_memo",)

    MAX_KEY_TOPICS = 16
    MAX_ENTRIES = 4096

    def __init__(self):
        self._memo = {}

    def get(self, topics, compute):
        """Return compute(key) memoized; ``key`` is the tuple form."""
        key = topics if type(topics) is tuple else tuple(topics)
        hit = self._memo.get(key)
        if hit is None:
            hit = compute(key)
            if len(key) <= self.MAX_KEY_TOPICS:
                if len(self._memo) >= self.MAX_ENTRIES:
                    self._memo.clear()
                self._memo[key] = hit
        return hit

    def __len__(self):
        return len(self._memo)


@dataclass(frozen=True)
class TopicSpace:
    """The set of valid topic values for a deployment.

    ``prune`` mirrors def.rs:37-51: strip unknown values, dedupe, and report
    whether anything was removed — the broker disconnects users that sent
    *only* invalid topics (tasks/user/handler.rs topic pruning).
    """

    valid: frozenset[int]

    def __post_init__(self):
        # prune() runs once per received broadcast on every broker, and
        # deployments publish a handful of distinct topic sets — memoize
        object.__setattr__(self, "_prune_memo", BoundedTopicMemo())

    @classmethod
    def from_enum(cls, topic_enum) -> "TopicSpace":
        return cls(frozenset(int(t) for t in topic_enum))

    @classmethod
    def range(cls, n: int) -> "TopicSpace":
        """Topic space 0..n-1 (bitmask-friendly; n ≤ 256)."""
        return cls(frozenset(range(n)))

    def prune(self, topics: Sequence[int]) -> tuple[tuple, bool]:
        """Return (valid-deduped-topics, had_invalid). The topic
        sequence comes back as an immutable TUPLE: results are shared by
        the memo, and a tuple makes that structurally safe."""
        def compute(key):
            seen = set()
            out: List[int] = []
            had_invalid = False
            for t in key:
                t = int(t)
                if t not in self.valid:
                    had_invalid = True
                    continue
                if t not in seen:
                    seen.add(t)
                    out.append(t)
            return tuple(out), had_invalid

        return self._prune_memo.get(topics, compute)

    def bitmask(self, topics: Iterable[int]) -> int:
        """Pack a topic set into an int bitmask (device representation)."""
        mask = 0
        for t in topics:
            mask |= 1 << int(t)
        return mask


TEST_TOPIC_SPACE = TopicSpace.from_enum(TestTopic)
