"""Shared protocol layer: wire format, transports, limiter, crypto, auth,
discovery, config registry.

Capability parity with the reference's ``cdn-proto`` crate (SURVEY.md §2a),
re-designed for a Python/asyncio host control plane feeding a JAX/TPU device
data plane.
"""

MAX_MESSAGE_SIZE = (2**32 - 1) // 8
"""Maximum wire message size in bytes (512 MiB-ish).

Parity: reference caps messages at ``u32::MAX / 8``
(cdn-proto/src/lib.rs:23-25).
"""
