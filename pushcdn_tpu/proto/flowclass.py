"""Flow-class taxonomy for per-class accounting (ISSUE 19).

Every frame the data plane moves belongs to one of four classes —
the measurement substrate ROADMAP item 4's per-class egress lanes
schedule over:

    0  control    broker/protocol traffic (auth, subscribe, sync)
    1  consensus  latency-critical application topics
    2  live       default pub/sub fan-out (Direct is always live)
    3  bulk       retention replay / catch-up floods

Topics map to classes by NAME through the :class:`TopicNamespace`
hierarchy (``consensus.*`` -> consensus, ``bulk.*`` -> bulk, ...), and
the resolved map compiles to a flat u8[256] table the native route-plan
kernel indexes per frame (class of a Broadcast = class of its FIRST
topic byte). Python senders resolve through the same table so the
scalar and pumped paths account identically.

The taxonomy is deployment config, not routing state: the compiled
table survives route-snapshot rebuilds, and a topic with no opinion
defaults to ``live``.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np

CONTROL = 0
CONSENSUS = 1
LIVE = 2
BULK = 3

N_CLASSES = 4
CLASS_NAMES: Tuple[str, ...] = ("control", "consensus", "live", "bulk")

# consumed-but-delivered-nowhere marker in per-frame class arrays
# (pruned-empty broadcast / unknown-recipient drop) — mirrors
# route_plan.cpp's out_class contract
CLASS_NONE = 255

# namespace prefixes that imply a class; first match wins, checked
# against the first dot-separated segment of the topic's bound name
_PREFIX_CLASSES = (
    ("control", CONTROL),
    ("consensus", CONSENSUS),
    ("bulk", BULK),
    ("replay", BULK),
)


def class_name(cls: int) -> str:
    return CLASS_NAMES[cls] if 0 <= cls < N_CLASSES else "none"


def class_of_name(name: Optional[str]) -> int:
    """Class implied by a hierarchical topic name (``live`` default)."""
    if name:
        head = name.split(".", 1)[0]
        for prefix, cls in _PREFIX_CLASSES:
            if head == prefix:
                return cls
    return LIVE


def compile_table(namespace=None, overrides=None) -> np.ndarray:
    """Compile the u8[256] topic -> class table the native planner and
    the Python senders share.

    ``namespace`` is a :class:`~pushcdn_tpu.proto.topic.TopicNamespace``
    (or None); every bound name contributes via :func:`class_of_name`.
    ``overrides`` maps raw topic ints to classes and wins over the
    namespace. Unmentioned topics are ``live``.
    """
    table = np.full(256, LIVE, np.uint8)
    if namespace is not None:
        for name, topic in namespace.bindings().items():
            if 0 <= topic <= 255:
                table[topic] = class_of_name(name)
    if overrides:
        for topic, cls in overrides.items():
            topic = int(topic)
            if 0 <= topic <= 255 and 0 <= int(cls) < N_CLASSES:
                table[topic] = int(cls)
    return table


_DEFAULT_TABLE = compile_table()

# process-wide active table: installed by the broker when it compiles
# its namespace, read by the scalar send paths. A flat module global —
# the hot paths index it with a single getitem.
_active_table: np.ndarray = _DEFAULT_TABLE


def install_table(table: np.ndarray) -> None:
    """Publish the active topic -> class table (u8[256])."""
    global _active_table
    table = np.ascontiguousarray(table, np.uint8)
    if table.shape == (256,):
        _active_table = table


def active_table() -> np.ndarray:
    return _active_table


def class_of_topics(topics) -> int:
    """Class of a Broadcast: its FIRST topic's class (``live`` when the
    topic list is empty) — the same rule route_plan.cpp applies."""
    for t in topics:
        t = int(t)
        if 0 <= t <= 255:
            return int(_active_table[t])
        break
    return LIVE


def frame_class(data) -> int:
    """Class of ONE serialized frame from its wire bytes — the shared
    sender/receiver rule the per-link conservation ledger tables use
    (ISSUE 20), so both ends of a mesh link classify identically:
    Broadcast → class of its first topic byte, Direct → ``live``, every
    other kind (auth / subscribe / sync / retained / control) →
    ``control``. Mirrors :func:`class_of_topics` and route_plan.cpp."""
    n = len(data)
    if not n:
        return CONTROL
    kind = data[0]
    if kind == 4 or kind == 0x84:        # Direct (plain / traced)
        return LIVE
    if kind == 5:                        # Broadcast: <u16 ntopics> topics
        if n >= 4 and (data[1] or data[2]):
            return int(_active_table[data[3]])
        return LIVE
    if kind == 0x85:                     # traced Broadcast (rare, sampled)
        try:
            from pushcdn_tpu.proto.message import unpack_trace
            _tr, off = unpack_trace(memoryview(data), 1)
            if n >= off + 3 and (data[off] or data[off + 1]):
                return int(_active_table[data[off + 2]])
        except Exception:
            pass
        return LIVE
    return CONTROL


def bincount_classes(classes: np.ndarray, lens=None):
    """(frames[4], bytes[4]) over a per-frame class array (u8; values
    >= N_CLASSES — e.g. CLASS_NONE — are excluded). ``lens`` adds 4
    bytes of length header per frame, matching the wire accounting."""
    classes = np.asarray(classes)
    keep = classes < N_CLASSES
    kept = classes[keep]
    frames = np.bincount(kept, minlength=N_CLASSES)[:N_CLASSES]
    if lens is None:
        return frames, None
    weights = np.asarray(lens)[keep] + 4
    nbytes = np.bincount(kept, weights=weights,
                         minlength=N_CLASSES)[:N_CLASSES]
    return frames, nbytes.astype(np.int64)
