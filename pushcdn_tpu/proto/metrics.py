"""Metrics: Prometheus-style text exposition over HTTP + core gauges.

Capability parity with cdn-proto/src/metrics.rs:18-78 (warp `/metrics`
endpoint, 30 s running-latency gauge computed from histogram deltas) and
cdn-proto/src/connection/metrics.rs:12-28 (BYTES_SENT / BYTES_RECV gauges,
LATENCY histogram of permit-allocation lifetime).

Dependency-free: a tiny registry + asyncio HTTP server producing the
Prometheus text format. Metrics are always collected (cheap int adds); the
endpoint is opt-in per binary, matching the reference's `metrics` feature.

Label support (ISSUE 4 registry upgrade): every metric type takes an
optional ``labels=(...)`` tuple of label NAMES; ``m.labels(name=value)``
returns (creating on first use) a child series that renders as
``name{label="value"} v`` and exposes the same mutator API — call sites
hold the child and pay a plain attribute call per update, exactly like
before. A labeled Counter also renders a bare total line (own value + the
children's sum) so pre-label dashboards keep working.

Thread-safety: mutators (``inc``/``set``/``observe``) and child creation
take one process-wide lock — native-code callers and bench threads observe
from off-loop threads, and an unlocked ``Histogram.observe`` loses updates
in its sum/bucket read-modify-write. The lock is uncontended in steady
state (hot paths update per *batch*, not per frame) and a render takes it
per-metric, so a scrape racing live updates sees each metric atomically.
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import sys
import threading
import time
from typing import Dict, List, Optional

_LOCK = threading.Lock()


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


class _LabeledMixin:
    """Shared child-series machinery. ``self._label_names`` is the declared
    label-name tuple (empty = unlabeled); ``self._labels`` is this series'
    own rendered ``k="v"`` pair string (children only)."""

    def _init_labels(self, labels) -> None:
        self._label_names = tuple(labels)
        self._labels = ""
        self._children: Dict[tuple, "_LabeledMixin"] = {}

    def labels(self, **kv):
        """The child series for these label values (create on first use).
        Raises ``KeyError`` on a label name that was not declared."""
        try:
            key = tuple(str(kv[n]) for n in self._label_names)
        except KeyError:
            raise KeyError(f"{self.name}: labels() requires exactly "
                           f"{self._label_names}, got {tuple(kv)}") from None
        if len(kv) != len(self._label_names):
            raise KeyError(f"{self.name}: labels() requires exactly "
                           f"{self._label_names}, got {tuple(kv)}")
        child = self._children.get(key)
        if child is None:
            with _LOCK:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    child._labels = ",".join(
                        f'{n}="{_escape_label(v)}"'
                        for n, v in zip(self._label_names, key))
                    self._children[key] = child
        return child

    def _sorted_children(self):
        return [self._children[k] for k in sorted(self._children)]


class Counter(_LabeledMixin):
    """Monotonic counter (exposed as prometheus counter)."""

    def __init__(self, name: str, help_: str, labels=()):
        self.name = name
        self.help = help_
        self.value = 0
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Counter":
        child = Counter.__new__(Counter)
        child.name, child.help, child.value = self.name, self.help, 0
        child._init_labels(())
        return child

    def inc(self, n: int = 1) -> None:
        with _LOCK:
            self.value += n

    def render(self, openmetrics: bool = False) -> str:
        # OpenMetrics mandates the _total suffix on counter SAMPLES (the
        # family name in TYPE/HELP stays bare); a strict OM parser —
        # Prometheus negotiates OM by default — rejects the whole scrape
        # otherwise. Plain 0.0.4 scrapes keep the historical bare names.
        suffix = "_total" if openmetrics else ""
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with _LOCK:
            total = self.value
            for child in self._sorted_children():
                total += child.value
                out.append(f"{self.name}{suffix}{{{child._labels}}}"
                           f" {child.value}")
            out.append(f"{self.name}{suffix} {total}")
        return "\n".join(out) + "\n"


class Gauge(_LabeledMixin):
    """Settable gauge."""

    def __init__(self, name: str, help_: str, labels=()):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Gauge":
        child = Gauge.__new__(Gauge)
        child.name, child.help, child.value = self.name, self.help, 0.0
        child._init_labels(())
        return child

    def set(self, v: float) -> None:
        with _LOCK:
            self.value = v

    def inc(self, n: float = 1) -> None:
        with _LOCK:
            self.value += n

    def dec(self, n: float = 1) -> None:
        with _LOCK:
            self.value -= n

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with _LOCK:
            for child in self._sorted_children():
                out.append(f"{self.name}{{{child._labels}}} {child.value}")
            if not self._label_names:
                out.append(f"{self.name} {self.value}")
            elif not self._children:
                # labeled gauge with no series yet: render nothing (a bare
                # 0 under set-semantics would be a lie)
                pass
        return "\n".join(out) + "\n"


class Histogram(_LabeledMixin):
    """Fixed-bucket histogram (seconds).

    Optional OpenMetrics exemplars: ``observe(v, exemplar={...})`` pins the
    given label dict (e.g. ``{"trace_id": "ab12..."}``) to the bucket the
    sample landed in; an OpenMetrics-negotiated scrape renders each
    bucket's most recent exemplar as ``# {trace_id="..."} value ts`` so a
    dashboard can jump from a latency bucket straight to the trace that
    populated it."""

    DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

    def __init__(self, name: str, help_: str, buckets=DEFAULT_BUCKETS,
                 labels=()):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self.exemplars: List[Optional[tuple]] = [None] * (len(self.buckets) + 1)
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Histogram":
        child = Histogram.__new__(Histogram)
        child.name, child.help = self.name, self.help
        child.buckets = self.buckets
        child.counts = [0] * (len(self.buckets) + 1)
        child.sum = 0.0
        child.total = 0
        child.exemplars = [None] * (len(self.buckets) + 1)
        child._init_labels(())
        return child

    def observe(self, v: float, exemplar: Optional[dict] = None) -> None:
        # The whole update is one critical section: sum/total/bucket are a
        # multi-step read-modify-write, and off-loop observers (native-code
        # callers, bench threads) would otherwise lose samples against the
        # event loop's updates.
        if exemplar is not None:
            exemplar = ("{" + ",".join(
                f'{k}="{_escape_label(val)}"'
                for k, val in exemplar.items()) + "}", v, time.time())
        with _LOCK:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    if exemplar is not None:
                        self.exemplars[i] = exemplar
                    return
            self.counts[-1] += 1
            if exemplar is not None:
                self.exemplars[-1] = exemplar

    def _render_series(self, out: List[str], labels: str,
                       exemplars: bool = False) -> None:
        sep = f"{labels}," if labels else ""
        cum = 0
        for i, (b, c) in enumerate(zip(self.buckets, self.counts)):
            cum += c
            line = f'{self.name}_bucket{{{sep}le="{b}"}} {cum}'
            ex = self.exemplars[i] if exemplars else None
            if ex is not None:
                line += f" # {ex[0]} {ex[1]} {ex[2]:.3f}"
            out.append(line)
        line = f'{self.name}_bucket{{{sep}le="+Inf"}} {self.total}'
        ex = self.exemplars[-1] if exemplars else None
        if ex is not None:
            line += f" # {ex[0]} {ex[1]} {ex[2]:.3f}"
        out.append(line)
        tail = f"{{{labels}}}" if labels else ""
        out.append(f"{self.name}_sum{tail} {self.sum}")
        out.append(f"{self.name}_count{tail} {self.total}")

    def render(self, exemplars: bool = False) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with _LOCK:
            for child in self._sorted_children():
                child._render_series(out, child._labels, exemplars)
            if not self._label_names:
                self._render_series(out, "", exemplars)
        return "\n".join(out) + "\n"


_REGISTRY: Dict[str, object] = {}
_BACKGROUND_TASKS: List[asyncio.Task] = []  # keep refs so GC can't kill them

# Core connection metrics (parity connection/metrics.rs:13-28, incremented
# by the transport layer at frame write/read). Labeled per transport — the
# connection caches its child at construction, so the hot path still pays
# one plain ``inc`` per flush.
BYTES_SENT = Counter("cdn_bytes_sent", "Total bytes written to peers",
                     labels=("transport",))
BYTES_RECV = Counter("cdn_bytes_received", "Total bytes read from peers",
                     labels=("transport",))
LATENCY = Histogram("cdn_message_latency_seconds",
                    "Permit-allocation lifetime: receive -> last fan-out send")
RUNNING_LATENCY = Gauge("cdn_running_latency_seconds",
                        "30s running average message latency")


def observe_message_latency(seconds: float) -> None:
    LATENCY.observe(seconds)


# Cut-through routing plane (broker/tasks/cutthrough.py): one native plan
# call routes a whole FrameChunk without per-frame Python. The histogram
# buckets are FRAME COUNTS per plan call, not seconds. The three per-path
# frame counters are one labeled family; the module attributes below are
# the cached children, so call sites stay `ROUTE_*_FRAMES.inc(n)`.
ROUTE_BATCH_SIZE = Histogram(
    "cdn_route_batch_size_frames",
    "Frames covered by one cut-through route-plan call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
ROUTE_FRAMES = Counter(
    "cdn_route_batch_frames",
    "Frames routed, by path: cutthrough = native plan (no per-frame "
    "Python), residual = handed to the scalar path by the plan (control/"
    "traced/malformed frames, depth-1 singles), scalar = routed entirely "
    "by the scalar receive loops",
    labels=("path",))
ROUTE_CUTTHROUGH_FRAMES = ROUTE_FRAMES.labels(path="cutthrough")
ROUTE_RESIDUAL_FRAMES = ROUTE_FRAMES.labels(path="residual")
ROUTE_SCALAR_FRAMES = ROUTE_FRAMES.labels(path="scalar")
# path=pump: the fused native pump planned AND sent the batch's hot
# frames (linked send SQEs prepped in C — zero Python per frame); a
# batch where every pair escalated still counts under path=cutthrough
ROUTE_PUMP_FRAMES = ROUTE_FRAMES.labels(path="pump")
PUMP_ESCALATIONS = Counter(
    "cdn_pump_escalations",
    "Frames (or whole batches, reason=control) the fused data-plane "
    "pump handed back to the Python path, by reason: unengaged = peer "
    "has no native slot (engagement is requested and happens at its "
    "next idle), fenced = a Python writer queue owns the peer's "
    "ordering right now, peer_error = a previous pumped chain errored, "
    "peer_error_event = a chain error disengaged a peer, chunk_slots = "
    "all native chunk-lease slots busy, control = a control/traced/"
    "malformed frame stopped the batch (scalar semantics), capacity = "
    "native peer table full at engagement",
    labels=("reason",))
ROUTE_TABLE_REBUILDS = Counter(
    "cdn_route_table_rebuilds",
    "Cut-through snapshot FULL rebuilds, by reason: first_build = cold "
    "start, version_gap = the delta log was trimmed past this snapshot's "
    "cursor, delta_overflow = more pending deltas than a rebuild costs, "
    "compaction = lazy-deletion garbage crossed the purge threshold, "
    "growth = peer slot capacity exhausted, retry = previous build "
    "failed allocation, incremental_disabled = the rebuild-per-"
    "invalidation baseline (PUSHCDN_ROUTE_INCREMENTAL=0)",
    labels=("reason",))
ROUTE_DELTAS_APPLIED = Counter(
    "cdn_route_deltas_applied",
    "Typed route deltas applied IN PLACE to the cut-through snapshot "
    "(the incremental alternative to a full rebuild, ISSUE 7)")
ROUTE_DELTA_APPLY_SECONDS = Histogram(
    "cdn_route_delta_apply_seconds",
    "Latency of one batched in-place delta application (Connections "
    "route-log suffix -> native table), O(delta) by construction",
    buckets=(1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.05, 0.5))

# Admission control / overload shedding (ISSUE 7): work REFUSED to keep
# the event loop alive, by tier. Every shed also records a flight-recorder
# event and flips the broker's /readyz "admission" check for
# PUSHCDN_SHED_READY_S so the load balancer steers away.
ROUTE_SHED = Counter(
    "cdn_route_shed_total",
    "Load-shed decisions by tier: user_conn / broker_conn = connection "
    "budget exceeded (PUSHCDN_MAX_CONNS_*), subscribe = per-connection "
    "subscribe/unsubscribe token bucket exhausted "
    "(PUSHCDN_SUBSCRIBE_RATE)",
    labels=("tier",))
ROUTE_SHED_USER_CONN = ROUTE_SHED.labels(tier="user_conn")
ROUTE_SHED_BROKER_CONN = ROUTE_SHED.labels(tier="broker_conn")
ROUTE_SHED_SUBSCRIBE = ROUTE_SHED.labels(tier="subscribe")

# Sharded data plane (broker/sharding.py): cross-shard handoff accounting.
# path=ring is the zero-copy shared-memory fast path; path=fallback is the
# counted drop-to-control-plane relay a full ring degrades to (the drain
# never blocks on a slow sibling).
SHARD_HANDOFF_RECORDS = Counter(
    "cdn_shard_handoff_records",
    "Cross-shard handoff records by path (ring = shared-memory, "
    "fallback = control-plane relay after ring-full)",
    labels=("path",))
SHARD_HANDOFF_RING = SHARD_HANDOFF_RECORDS.labels(path="ring")
SHARD_HANDOFF_FALLBACK = SHARD_HANDOFF_RECORDS.labels(path="fallback")
SHARD_HANDOFF_SHED = SHARD_HANDOFF_RECORDS.labels(path="shed")
SHARD_HANDOFF_FRAMES = Counter(
    "cdn_shard_handoff_frames",
    "Frames carried by cross-shard handoff records", labels=("path",))
SHARD_HANDOFF_FRAMES_RING = SHARD_HANDOFF_FRAMES.labels(path="ring")
SHARD_HANDOFF_FRAMES_FALLBACK = SHARD_HANDOFF_FRAMES.labels(path="fallback")
SHARD_HANDOFF_FRAMES_SHED = SHARD_HANDOFF_FRAMES.labels(path="shed")
SHARD_RING_TORN = Counter(
    "cdn_shard_ring_torn_reads",
    "Cross-shard ring drains that backed off on a torn/uncommitted record")
SHARD_RING_POISONED = Counter(
    "cdn_shard_ring_poisoned",
    "Inbound rings abandoned because a record never committed (producer "
    "died mid-push or slot corruption); traffic falls back to the relay")
SHARD_DELTAS_APPLIED = Counter(
    "cdn_shard_deltas_applied",
    "Control-plane interest deltas applied from sibling shards")

# Egress fan-out accounting by peer type (EgressBatch.flush / the
# cut-through _send_plan increment batch-wise).
EGRESS_FRAMES = Counter(
    "cdn_egress_frames",
    "Frames handed to connection writers, by destination peer type",
    labels=("peer",))
EGRESS_FRAMES_USER = EGRESS_FRAMES.labels(peer="user")
EGRESS_FRAMES_BROKER = EGRESS_FRAMES.labels(peer="broker")

# Writer-queue depth across live connections (refreshed at render by a
# pre-render hook over the transport layer's connection registry) and
# event-loop lag (sampled by a supervised background task).
WRITER_QUEUE_DEPTH = Gauge(
    "cdn_writer_queue_depth",
    "Entries waiting in connection send queues (stat=sum|max across "
    "live connections)",
    labels=("stat",))
EVENT_LOOP_LAG = Gauge(
    "cdn_event_loop_lag_seconds",
    "How late the event loop ran a sleep(0.25) wakeup (scheduling lag)")

# Global memory-pool occupancy (refreshed at render from the limiter's
# live-pool registry).
POOL_BYTES = Gauge(
    "cdn_pool_bytes",
    "Global byte-pool permit accounting across live pools "
    "(state=in_use|capacity)",
    labels=("state",))

# -- per-class flow accounting (ISSUE 19) -----------------------------------
# Classes come from proto/flowclass.py (0=control 1=consensus 2=live
# 3=bulk). dir=out counts fan-out deliveries (one per (frame, peer)
# pair, stamped BEFORE the connection lookup so the scalar, cut-through
# and pumped paths count identically); dir=in counts consumed ingress
# frames. Both the Python writer and the native pump feed the same
# families, so the split stays comparable across engagement changes.
_CLASS_NAMES = ("control", "consensus", "live", "bulk")
CLASS_FRAMES = Counter(
    "cdn_class_frames",
    "Frames moved per flow class (dir=in consumed ingress, dir=out "
    "fan-out deliveries; taxonomy per proto/flowclass.py)",
    labels=("class", "dir"))
CLASS_BYTES = Counter(
    "cdn_class_bytes",
    "Wire bytes (payload + 4-byte length header) per flow class",
    labels=("class", "dir"))
CLASS_FRAMES_OUT = tuple(CLASS_FRAMES.labels(**{"class": c, "dir": "out"})
                         for c in _CLASS_NAMES)
CLASS_FRAMES_IN = tuple(CLASS_FRAMES.labels(**{"class": c, "dir": "in"})
                        for c in _CLASS_NAMES)
CLASS_BYTES_OUT = tuple(CLASS_BYTES.labels(**{"class": c, "dir": "out"})
                        for c in _CLASS_NAMES)
CLASS_BYTES_IN = tuple(CLASS_BYTES.labels(**{"class": c, "dir": "in"})
                       for c in _CLASS_NAMES)

WRITER_QUEUE_DELAY = Histogram(
    "cdn_writer_queue_delay_seconds",
    "Head-of-line delay per flow class: writer-queue enqueue -> the "
    "writer loop dequeuing the entry (the ROADMAP item-4 scheduling "
    "input; inline fast-path sends never queue and are not observed)",
    buckets=(1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
             1.0, 5.0),
    labels=("class",))
WRITER_QUEUE_DELAY_CLS = tuple(WRITER_QUEUE_DELAY.labels(**{"class": c})
                               for c in _CLASS_NAMES)

# Per-peer writer-queue depth: the top-K deepest connections by label,
# refreshed at render. The rest fold into peer="other"; the family's
# cardinality is capped like the task profiler's (a runaway connection
# churn must not bloat every scrape forever).
WRITER_QUEUE_DEPTH_PEER = Gauge(
    "cdn_writer_queue_depth_peer",
    "Send-queue depth of the deepest live connections (top-K by depth; "
    "the rest aggregate under peer=\"other\")",
    labels=("peer",))

# Retention / replay observability (ISSUE 19 tentpole 3): refreshed at
# render by broker/retention.py's pre-render hook over live stores.
RETENTION_RING_BYTES = Gauge(
    "cdn_retention_ring_bytes",
    "Payload bytes resident in durable-topic retention rings",
    labels=("topic",))
RETENTION_RING_ENTRIES = Gauge(
    "cdn_retention_ring_entries",
    "Entries resident in durable-topic retention rings",
    labels=("topic",))
RETENTION_EVICTIONS = Counter(
    "cdn_retention_evictions",
    "Retention-ring evictions by reason (bytes = per-topic byte budget, "
    "entries = per-topic entry budget, age = max-age expiry)",
    labels=("reason",))
REPLAY_LAG = Gauge(
    "cdn_replay_lag_entries",
    "Entries between a replaying subscriber's cursor and the retention "
    "ring head (top-K laggards; the rest aggregate under "
    "subscriber=\"other\")",
    labels=("subscriber",))


# -- native shm telemetry (ISSUE 19 tentpole 1) -----------------------------
# The uring engine + fused pump accumulate log2-ns histograms into a
# lock-free shared block written from C (zero hot-path Python). A
# pre-render hook (registered by proto/transport/uring.py) snapshots it
# and pushes the aggregate here; these classes only RENDER.

# rendered bucket window: fold sub-256ns into the first bucket's
# cumulative count, stop explicit buckets at ~1100s (the remainder only
# shows in +Inf) — a fixed layout so scrapes compare across processes
_TM_LO_BUCKET = 8
_TM_HI_BUCKET = 40


class _NativeLog2Histogram:
    """Prometheus histogram family rendered from a native log2-ns
    telemetry snapshot. ``update`` replaces a label's series wholesale
    (the native block is the source of truth; values are monotonic
    because closing engines fold their final snapshot into a carry)."""

    def __init__(self, name: str, help_: str, label: str):
        self.name = name
        self.help = help_
        self.label = label
        self.series: Dict[str, dict] = {}
        _REGISTRY[name] = self

    def update(self, value: str, hist: dict) -> None:
        self.series[value] = hist

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for val in sorted(self.series):
            h = self.series[val]
            lab = f'{self.label}="{_escape_label(val)}"'
            cum = 0
            for k, c in enumerate(h["buckets"]):
                cum += c
                if k < _TM_LO_BUCKET or k > _TM_HI_BUCKET:
                    continue
                le = float(1 << k) / 1e9
                out.append(f'{self.name}_bucket{{{lab},le="{le:.9g}"}} '
                           f'{cum}')
            out.append(f'{self.name}_bucket{{{lab},le="+Inf"}} '
                       f'{h["count"]}')
            out.append(f'{self.name}_sum{{{lab}}} {h["sum_ns"] / 1e9}')
            out.append(f'{self.name}_count{{{lab}}} {h["count"]}')
        return "\n".join(out) + "\n"


PUMP_STAGE_SECONDS = _NativeLog2Histogram(
    "cdn_pump_stage_seconds",
    "Native pump stage latency stamped from C with CLOCK_MONOTONIC "
    "(stage=plan: recv-CQE -> route-plan done; submit: plan -> SQE "
    "staged; wire: SQE submit -> send-CQE; total: recv-CQE -> "
    "send-CQE)", "stage")
URING_CHAIN_SECONDS = _NativeLog2Histogram(
    "cdn_uring_chain_seconds",
    "io_uring engine timing (stat=enter: one io_uring_enter syscall "
    "wall time; chain: pumped linked-chain submit -> quiesce)", "stat")
PUMP_CLASS_DELAY_SECONDS = _NativeLog2Histogram(
    "cdn_pump_class_delay_seconds",
    "Pumped per-frame recv -> send-CQE delay by flow class", "class")

# last folded native class totals (the pumped counters are monotonic
# aggregates: live engines + closed-engine carry; fold only the delta)
_native_class_last: Dict[tuple, int] = {}


def update_native_telemetry(totals: Optional[dict]) -> None:
    """Publish one aggregated native telemetry snapshot (the output of
    ``native.uring.parse_telemetry`` summed over live engines plus the
    closed-engine carry). Called by the transport's pre-render hook;
    histograms are replaced, pumped class counters fold by delta into
    the shared cdn_class_* families (dir=out)."""
    if not totals:
        return
    for stage, h in totals["stage"].items():
        PUMP_STAGE_SECONDS.update(stage, h)
    for stat, h in totals["chain"].items():
        URING_CHAIN_SECONDS.update(stat, h)
    for cls, h in totals["class_delay"].items():
        PUMP_CLASS_DELAY_SECONDS.update(cls, h)
    # lazy: ledger.py imports this module for its metric families
    from pushcdn_tpu.proto import ledger as ledger_mod
    for i, cls in enumerate(_CLASS_NAMES):
        for kind, child_row, series in (
                ("frames", CLASS_FRAMES_OUT, totals["class_frames"]),
                ("bytes", CLASS_BYTES_OUT, totals["class_bytes"])):
            cur = int(series.get(cls, 0))
            last = _native_class_last.get((kind, cls), 0)
            if cur > last:
                child_row[i].inc(cur - last)
            _native_class_last[(kind, cls)] = max(cur, last)
        # conservation fold (ISSUE 20): a pumped frame's queued credit and
        # terminal fate land in the SAME delta (delivered = class_frames,
        # dropped = fate_drop_frames), so pump in-flight is invisible to
        # the identity by construction and the balance sheet never shows
        # a transient pumped deficit.
        delivered = 0
        cur = int(totals["class_frames"].get(cls, 0))
        last = _native_class_last.get(("ledger_frames", cls), 0)
        if cur > last:
            delivered = cur - last
        _native_class_last[("ledger_frames", cls)] = max(cur, last)
        dropped = 0
        cur = int(totals.get("class_drop_frames", {}).get(cls, 0))
        last = _native_class_last.get(("ledger_drops", cls), 0)
        if cur > last:
            dropped = cur - last
        _native_class_last[("ledger_drops", cls)] = max(cur, last)
        if delivered or dropped:
            ledger_mod.note_queued(i, delivered + dropped)
            if delivered:
                ledger_mod.record_fate("delivered", "pumped", i, delivered)
            if dropped:
                ledger_mod.record_fate("dropped", "pump_peer_poison", i,
                                       dropped)


# Callables run before every render: components whose counters move on
# hot paths (device-plane steps) register a refresh here instead of
# pushing gauge updates from their pump loops.
PRE_RENDER_HOOKS: list = []

# BLS per-public-key Miller line-table cache (native/bls_bn254.cpp): the
# auth hot path's amortization state. One labeled gauge family (not
# counters, because the native library owns the monotonic values and a
# cache clear legitimately zeroes them); module attributes are the cached
# children so existing call sites keep working.
BLS_PK_CACHE = Gauge("cdn_bls_pk_cache",
                     "BLS verify line-table cache state "
                     "(stat=hits|misses|evictions|entries|bytes)",
                     labels=("stat",))
BLS_PK_CACHE_HITS = BLS_PK_CACHE.labels(stat="hits")
BLS_PK_CACHE_MISSES = BLS_PK_CACHE.labels(stat="misses")
BLS_PK_CACHE_EVICTIONS = BLS_PK_CACHE.labels(stat="evictions")
BLS_PK_CACHE_ENTRIES = BLS_PK_CACHE.labels(stat="entries")
BLS_PK_CACHE_BYTES = BLS_PK_CACHE.labels(stat="bytes")

# Client-side live gap detector (ISSUE 20): the subscriber's view of
# the frame-fate ledger. A gap EVENT is a sequence hole opening in a
# stream the client follows (frames skipped past); a HEAL is a late
# arrival filling a tracked hole (an at-least-once redelivery or
# reorder — legal). Outstanding loss as the client sees it is
# events - healed; wrap-up loss checks read these live counters
# instead of post-hoc log diffing. Duplicates never touch either.
CLIENT_GAP_EVENTS = Counter(
    "cdn_client_gap_events",
    "Delivery-sequence holes opened in streams this client follows "
    "(frames skipped past; late arrivals may still heal them)")
CLIENT_GAP_HEALED = Counter(
    "cdn_client_gap_healed",
    "Previously-open delivery gaps filled by a late arrival "
    "(at-least-once redelivery or reorder — legal)")

# Message-lifecycle tracing (proto/trace.py): per-hop latency from the
# traced message's origin. Defined here (not in trace.py) so every
# /metrics endpoint exposes the family even before the first span.
TRACE_HOP_LATENCY = Histogram(
    "cdn_trace_hop_seconds",
    "Time from a traced message's origin to each lifecycle hop "
    "(hop=publish|auth|ingress|plan|egress|delivery)",
    labels=("hop",))

# End-to-end SLO histogram (ISSUE 5): recorded at DELIVERY from the traced
# message's carried origin_ns — the publish→delivery latency an end user
# experienced, with OpenMetrics exemplars pinning each bucket to the trace
# id that last landed there (scrape with Accept: application/openmetrics-
# text to see them; plain scrapes omit exemplars for strict 0.0.4 parsers).
E2E_LATENCY = Histogram(
    "cdn_e2e_latency_seconds",
    "End-to-end publish->delivery latency of traced messages, recorded at "
    "delivery from the carried origin timestamp (single-machine clocks; "
    "cross-machine skew applies)",
    buckets=(5e-4, 1e-3, 2.5e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0))

# Monotonic-clock accounting around the native seams we own: one
# perf_counter pair per *batch-level* call (route plan per chunk, egress
# encode per fan-out batch, BLS verify per handshake), so a scrape answers
# "is the loop hot in planning, egress, or auth" without a debugger.
NATIVE_SECONDS = Counter(
    "cdn_native_seconds",
    "Cumulative wall-clock seconds inside instrumented native seams "
    "(kernel=route_plan|egress_encode|bls_verify)",
    labels=("kernel",))
NATIVE_PLAN_SECONDS = NATIVE_SECONDS.labels(kernel="route_plan")
NATIVE_EGRESS_SECONDS = NATIVE_SECONDS.labels(kernel="egress_encode")
NATIVE_BLS_SECONDS = NATIVE_SECONDS.labels(kernel="bls_verify")

# Per-task sampling profiler (ISSUE 5): every tick the profiler walks
# asyncio.all_tasks() and attributes one sample per live task to its task
# FAMILY (the task name with trailing ids/counters stripped, so every
# "user-receive" connection task lands in one series). samples x interval
# ~= task-alive wall-clock seconds; comparing families across scrapes
# shows where the loop's task population grows or leaks.
TASK_SAMPLES = Counter(
    "cdn_task_samples",
    "Sampling profiler: one sample per live asyncio task per tick, "
    "labeled by task family (samples x PUSHCDN_PROFILE_INTERVAL "
    "~= task-alive seconds)",
    labels=("task",))

# Build/runtime identity: one constant-1 series whose labels carry the
# package version, jax version, and the ACTUAL backend/device kind —
# so "ALIVE but device_kind=cpu" (TPU_PROBES r5/r6) is visible on every
# scrape instead of buried in a probes file.
BUILD_INFO = Gauge("cdn_build_info",
                   "Build/runtime identity (value is always 1)",
                   labels=("version", "jax", "backend", "device_kind"))


# Host I/O engine identity: which data-plane impl this process resolved
# (--io-impl auto can honestly demote to asyncio when the kernel denies
# io_uring — the label is set at resolution time, value always 1)
IO_IMPL = Gauge("cdn_io_impl",
                "Resolved host I/O data-plane impl (value is always 1)",
                labels=("impl",))


_build_info_last: tuple = ()


def _refresh_build_info() -> None:
    """(Re)probe cdn_build_info at every render — the backend can
    initialize AFTER the first scrape (a broker attaches its device plane
    lazily), and a frozen 'uninitialized' label would defeat the point.
    The stale series drops to 0 and the current one reads 1. Never
    *initializes* jax: a broker that never touched an accelerator must
    not pay a multi-second backend probe inside its /metrics handler —
    unimported jax reports backend=unloaded, imported-but-uninitialized
    reports uninitialized (jax.devices() on an already-initialized
    backend is a cached lookup)."""
    global _build_info_last
    import pushcdn_tpu
    jax_mod = sys.modules.get("jax")
    jax_v = getattr(jax_mod, "__version__", "absent") if jax_mod else "absent"
    backend = "unloaded"
    device_kind = "unknown"
    if jax_mod is not None:
        try:
            # peek, never provoke: only report devices when a backend has
            # already been initialized by the process' own work
            backends = getattr(
                sys.modules.get("jax._src.xla_bridge"), "_backends", None)
            if backends:
                dev = jax_mod.devices()[0]
                backend = dev.platform
                device_kind = dev.device_kind
            else:
                backend = "uninitialized"
        except Exception:
            backend = "error"
    current = (pushcdn_tpu.__version__, jax_v, backend, device_kind)
    if current == _build_info_last:
        return
    if _build_info_last:
        BUILD_INFO.labels(version=_build_info_last[0], jax=_build_info_last[1],
                          backend=_build_info_last[2],
                          device_kind=_build_info_last[3]).set(0)
    BUILD_INFO.labels(version=current[0], jax=current[1], backend=current[2],
                      device_kind=current[3]).set(1)
    _build_info_last = current


def _refresh_bls_pk_cache() -> None:
    from pushcdn_tpu.native import bls
    # peek, never provoke: pk_cache_stats() would lazily COMPILE the
    # native library (a multi-second synchronous g++ run) and this hook
    # runs inside the asyncio /metrics handler — a process that never
    # verified a BLS signature keeps the gauges at zero instead
    if not bls.loaded():
        return
    stats = bls.pk_cache_stats()
    if stats is None:  # native library unavailable: gauges stay zero
        return
    BLS_PK_CACHE_HITS.set(stats["hits"])
    BLS_PK_CACHE_MISSES.set(stats["misses"])
    BLS_PK_CACHE_EVICTIONS.set(stats["evictions"])
    BLS_PK_CACHE_ENTRIES.set(stats["entries"])
    BLS_PK_CACHE_BYTES.set(stats["bytes"])


def register_bls_pk_cache_metrics() -> None:
    """Idempotent: pull the native cache counters into the gauges on
    every render. Registered by processes that actually verify BLS
    signatures (the marshal; brokers via their auth path) — a process
    that never loads the native library keeps the hook a no-op."""
    if _refresh_bls_pk_cache not in PRE_RENDER_HOOKS:
        PRE_RENDER_HOOKS.append(_refresh_bls_pk_cache)


_TOP_K_QUEUE_PEERS = 8
_MAX_PEER_SERIES = 64  # created-children cap, like the task profiler's
_peer_depth_live: set = set()


def _refresh_writer_queues() -> None:
    """Sum/max of send-queue depths across live connections (the transport
    layer keeps a weak registry), plus the top-K deepest peers by label —
    the head-of-line victim is invisible in an aggregate. Lazy module
    lookup: a process that never created a connection reports zeros
    without importing the transport."""
    global _peer_depth_live
    base = sys.modules.get("pushcdn_tpu.proto.transport.base")
    total = depth_max = 0
    depths = []
    if base is not None:
        for conn in list(base.LIVE_CONNECTIONS):
            try:
                d = conn._send_q.qsize()
            except Exception:
                continue
            total += d
            if d > depth_max:
                depth_max = d
            if d > 0:
                depths.append((d, getattr(conn, "label", "?")))
    WRITER_QUEUE_DEPTH.labels(stat="sum").set(total)
    WRITER_QUEUE_DEPTH.labels(stat="max").set(depth_max)
    depths.sort(key=lambda t: (-t[0], t[1]))
    live = set()
    other = 0
    for rank, (d, label) in enumerate(depths):
        # bounded cardinality: only top-K rank a series, and a label that
        # would grow the family past the cap folds into "other" too
        if rank >= _TOP_K_QUEUE_PEERS or (
                (label,) not in WRITER_QUEUE_DEPTH_PEER._children
                and len(WRITER_QUEUE_DEPTH_PEER._children)
                >= _MAX_PEER_SERIES):
            other += d
            continue
        WRITER_QUEUE_DEPTH_PEER.labels(peer=label).set(d)
        live.add(label)
    WRITER_QUEUE_DEPTH_PEER.labels(peer="other").set(other)
    live.add("other")
    for stale in _peer_depth_live - live:
        WRITER_QUEUE_DEPTH_PEER.labels(peer=stale).set(0)
    _peer_depth_live = live


def _refresh_pools() -> None:
    """Global byte-pool occupancy across live pools (limiter registry)."""
    limiter_mod = sys.modules.get("pushcdn_tpu.proto.limiter")
    in_use = capacity = 0
    if limiter_mod is not None:
        for pool in list(limiter_mod.LIVE_POOLS):
            capacity += pool.capacity
            in_use += pool.capacity - pool.available
    POOL_BYTES.labels(state="in_use").set(in_use)
    POOL_BYTES.labels(state="capacity").set(capacity)


PRE_RENDER_HOOKS.append(_refresh_build_info)
PRE_RENDER_HOOKS.append(_refresh_writer_queues)
PRE_RENDER_HOOKS.append(_refresh_pools)


_hook_failures: set = set()


def render_all(openmetrics: bool = False) -> str:
    for hook in list(PRE_RENDER_HOOKS):
        try:
            hook()
        except Exception:
            # a broken hook must not take down /metrics, but a silently
            # frozen gauge is an operator trap — log each hook ONCE
            if id(hook) not in _hook_failures:
                _hook_failures.add(id(hook))
                logging.getLogger("pushcdn.metrics").exception(
                    "metrics pre-render hook %r failed; its gauges are "
                    "stale from here on", hook)
    parts = []
    for m in list(_REGISTRY.values()):
        if openmetrics and isinstance(m, Histogram):
            parts.append(m.render(exemplars=True))
        elif openmetrics and isinstance(m, Counter):
            parts.append(m.render(openmetrics=True))
        else:
            parts.append(m.render())
    if openmetrics:
        parts.append("# EOF\n")
    return "".join(parts)


def render_tasks() -> str:
    """One line per live asyncio task: name, state, and where it is
    suspended — the poor man's tokio-console (`GET /tasks`)."""
    lines = []
    for task in sorted(asyncio.all_tasks(), key=lambda t: t.get_name()):
        # Task.cancelling is 3.11+; 3.10 images just report pending
        _cancelling = getattr(task, "cancelling", None)
        state = "done" if task.done() else (
            "cancelling" if _cancelling is not None and _cancelling()
            else "pending")
        where = ""
        if not task.done():
            stack = task.get_stack(limit=1)
            if stack:
                frame = stack[-1]
                where = f" @ {frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
        lines.append(f"{task.get_name()}  [{state}]{where}")
    return f"{len(lines)} tasks\n" + "\n".join(lines) + "\n"


def supervised(factory, name: str, restart_delay_s: float = 1.0):
    """Run ``await factory()`` forever, logging + restarting on exception
    instead of letting the task die silently for the rest of the process
    lifetime (the pre-ISSUE-4 fate of ``_running_latency_calculator``).
    Each death is recorded in the process flight recorder so the trail
    shows up in ``/debug/flightrec`` and the diagnostics log."""
    from pushcdn_tpu.proto import flightrec

    async def _runner():
        rec = flightrec.task_recorder()
        while True:
            try:
                await factory()
                rec.record("task-exited", name)
                logging.getLogger("pushcdn.metrics").warning(
                    "supervised task %r returned; restarting", name)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                rec.record("task-died", f"{name}: {exc!r}", abnormal=True)
                logging.getLogger("pushcdn.metrics").exception(
                    "supervised task %r died; restarting in %.1fs",
                    name, restart_delay_s)
            await asyncio.sleep(restart_delay_s)

    return _runner()


async def _running_latency_calculator(interval_s: float = 30.0) -> None:
    """Recompute RUNNING_LATENCY from histogram deltas every ``interval_s``
    (parity metrics.rs:43-78)."""
    prev_sum, prev_total = LATENCY.sum, LATENCY.total
    while True:
        await asyncio.sleep(interval_s)
        ds, dn = LATENCY.sum - prev_sum, LATENCY.total - prev_total
        RUNNING_LATENCY.set(ds / dn if dn else 0.0)
        prev_sum, prev_total = LATENCY.sum, LATENCY.total


_loop_lag_peak = 0.0


def _refresh_loop_lag() -> None:
    """Publish the PEAK lag since the last scrape, then reset. A plain
    last-sample gauge would be overwritten by the next on-time wakeup
    ~interval later, hiding every stall shorter than the scrape interval
    — exactly the incidents the metric exists to surface."""
    global _loop_lag_peak
    EVENT_LOOP_LAG.set(_loop_lag_peak)
    _loop_lag_peak = 0.0


PRE_RENDER_HOOKS.append(_refresh_loop_lag)


# most recent single sample, never reset by a scrape — what /healthz
# reads (a loop so wedged the sampler can't run can't answer /healthz
# either, so the probe's own timeout covers total stalls)
_loop_lag_last = 0.0


async def _loop_lag_sampler(interval_s: float = 0.25) -> None:
    """Sample event-loop scheduling lag: how late a sleep() wakeup ran.
    A loop hogged by a long synchronous section (native call, giant
    decode) shows up here before it shows up as user-visible latency.
    Samples accumulate as a max; the pre-render hook publishes-and-resets
    per scrape."""
    global _loop_lag_peak, _loop_lag_last
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(interval_s)
        lag = loop.time() - t0 - interval_s
        _loop_lag_last = lag
        if lag > _loop_lag_peak:
            _loop_lag_peak = lag


# ---------------------------------------------------------------------------
# per-task sampling profiler (ISSUE 5)
# ---------------------------------------------------------------------------

def profile_interval_s() -> float:
    """Profiler tick from ``PUSHCDN_PROFILE_INTERVAL`` (seconds; default
    0.25, ``0`` disables the sampler entirely)."""
    raw = os.environ.get("PUSHCDN_PROFILE_INTERVAL", "").strip()
    if not raw:
        return 0.25
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return 0.25


# "user-receive-7f3a" / "Task-12" / "dial-0x7f.." → one family each;
# iteratively strip trailing counters and hex-ish ids
_FAMILY_STRIP = re.compile(r"[-_.:]?(?:0x)?[0-9a-fA-F]{4,}$|[-_.:]?\d+$")
_MAX_TASK_FAMILIES = 64

_family_children: Dict[str, Counter] = {}


def _task_family(name: str) -> str:
    while True:
        stripped = _FAMILY_STRIP.sub("", name)
        if stripped == name:
            break
        name = stripped
    return name or "anonymous"


def _family_child(family: str) -> Counter:
    child = _family_children.get(family)
    if child is None:
        # bounded cardinality: past the cap, new families fold into
        # "other" (a runaway label set would bloat every scrape forever)
        if len(_family_children) >= _MAX_TASK_FAMILIES \
                and family != "other":
            return _family_child("other")
        child = TASK_SAMPLES.labels(task=family)
        _family_children[family] = child
    return child


async def _task_profiler(interval_s: Optional[float] = None) -> None:
    """The sampling profiler task: each tick attributes one sample per
    live asyncio task to its family. Cost per tick is one all_tasks()
    snapshot + a dict count — at the default 0.25 s interval this is
    noise even with thousands of connection tasks (A/B'd in
    benches/route_bench.py under the 2% forwarding budget)."""
    if interval_s is None:
        interval_s = profile_interval_s()
    if interval_s <= 0:
        # disabled (PUSHCDN_PROFILE_INTERVAL=0): park instead of
        # busy-looping on sleep(0) — direct spawners (benches) and a
        # supervised() wrapper both stay quiet
        await asyncio.Event().wait()
        return
    name_cache: Dict[str, str] = {}
    while True:
        await asyncio.sleep(interval_s)
        counts: Dict[str, int] = {}
        for task in asyncio.all_tasks():
            if task.done():
                continue
            name = task.get_name()
            # unnamed tasks ("Task-<n>") are the dominant population on a
            # loaded broker and every name is unique — a cache keyed on
            # the full name would thrash, and running the regex per task
            # per tick is exactly the loop stall this profiler hunts
            if name.startswith("Task-") and name[5:].isdigit():
                family = "Task"
            else:
                family = name_cache.get(name)
                if family is None:
                    if len(name_cache) > 4 * _MAX_TASK_FAMILIES:
                        name_cache.clear()  # renamed-task churn bound
                    family = name_cache[name] = _task_family(name)
            counts[family] = counts.get(family, 0) + 1
        for family, n in counts.items():
            _family_child(family).inc(n)


# ---------------------------------------------------------------------------
# HTTP endpoint: parsed request line + route table (ISSUE 5)
# ---------------------------------------------------------------------------

# Extra debug routes registered by components (the broker's
# /debug/topology). A provider is ``fn(params) -> dict`` (rendered as
# JSON) or ``-> (status, content_type, body_str)``; it may be async.
DEBUG_ROUTES: Dict[str, object] = {}


def register_debug_route(path: str, provider) -> None:
    DEBUG_ROUTES[path] = provider


def unregister_debug_route(path: str) -> None:
    DEBUG_ROUTES.pop(path, None)


def _check_loop_lag():
    """Built-in liveness: the most recent loop-lag sample under threshold
    (``PUSHCDN_HEALTH_LAG_MAX`` seconds, default 2.0). A loop so wedged
    the sampler can't run at all can't answer /healthz either — the
    probe's own timeout covers that case."""
    try:
        limit = float(os.environ.get("PUSHCDN_HEALTH_LAG_MAX", "") or 2.0)
    except ValueError:
        limit = 2.0
    lag = _loop_lag_last
    return lag < limit, f"last loop-lag sample {lag * 1e3:.1f}ms (limit {limit:.1f}s)"


def _check_samplers():
    """Built-in liveness: the supervised background samplers are alive
    (supervised() restarts them on death, so a done task here means the
    supervisor itself died). Only THIS loop's tasks count — a leftover
    set from a torn-down loop (in-process restarts, tests) is pruned by
    the next serve_metrics, not a liveness failure."""
    loop = asyncio.get_running_loop()
    mine = [t for t in _BACKGROUND_TASKS if t.get_loop() is loop]
    dead = [t.get_name() for t in mine if t.done()]
    if dead:
        return False, f"dead: {','.join(dead)}"
    return True, f"{len(mine)} supervised samplers running"


def _parse_qs(query: str) -> Dict[str, str]:
    params: Dict[str, str] = {}
    for part in query.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        params[k] = v
    return params


async def serve_metrics(bind_endpoint: str) -> asyncio.AbstractServer:
    """Serve the observability endpoints over a parsed, routed HTTP/1.1
    GET surface (the pre-ISSUE-5 substring dispatch served the flightrec
    body to any request merely *containing* ``/debug/flightrec``, e.g. in
    a query string):

    - ``GET /metrics`` — Prometheus text (parity metrics.rs:18-39); with
      ``Accept: application/openmetrics-text`` the body carries bucket
      exemplars (trace ids on ``cdn_e2e_latency_seconds``) and ``# EOF``.
    - ``GET /healthz`` / ``GET /readyz`` — liveness/readiness JSON
      (:mod:`pushcdn_tpu.proto.health`); 503 when a check fails or the
      process is draining. Never initializes jax.
    - ``GET /tasks`` — asyncio task dump (the poor man's tokio-console).
    - ``GET /debug/flightrec[?limit=N]`` — live flight-recorder trails,
      capped at N events total (default 10000).
    - ``GET /debug/...`` — component-registered routes (broker:
      ``/debug/topology``).

    Non-GET methods get 405, unknown paths 404, a garbled request line
    400. Returns the server; also spawns the supervised background
    samplers (running-latency calculator, event-loop-lag sampler, task
    profiler) and registers the built-in liveness checks.
    """
    from pushcdn_tpu.proto import flightrec, health
    from pushcdn_tpu.proto.error import parse_endpoint
    host, port = parse_endpoint(bind_endpoint)

    def _resp(status: int, body: bytes,
              content_type: str = "text/plain",
              extra_headers: str = "") -> bytes:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed",
                  503: "Service Unavailable"}.get(status, "OK")
        return (f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{extra_headers}\r\n".encode() + body)

    async def _route(method: str, path: str, params: Dict[str, str],
                     headers: Dict[str, str]) -> bytes:
        if method != "GET":
            return _resp(405, b"only GET is supported\n",
                         extra_headers="Allow: GET\r\n")
        if path == "/metrics":
            om = "openmetrics" in headers.get("accept", "")
            return _resp(200, render_all(openmetrics=om).encode(),
                         "application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8" if om
                         else "text/plain; version=0.0.4")
        if path == "/healthz":
            status, body = await health.render_healthz()
            return _resp(status, body.encode(), "application/json")
        if path == "/readyz":
            status, body = await health.render_readyz()
            return _resp(status, body.encode(), "application/json")
        if path == "/tasks":
            # async-runtime introspection (the reference wires
            # tokio-console behind tokio_unstable; here a plain dump of
            # every live asyncio task: name, state, current frame)
            return _resp(200, render_tasks().encode())
        if path == "/debug/flightrec":
            try:
                limit = int(params.get("limit", ""))
            except ValueError:
                limit = None
            return _resp(200, flightrec.render_all(limit=limit).encode())
        provider = DEBUG_ROUTES.get(path)
        if provider is not None:
            result = provider(params)
            if asyncio.iscoroutine(result):
                result = await result
            if isinstance(result, dict):
                import json as json_mod
                return _resp(200, (json_mod.dumps(result) + "\n").encode(),
                             "application/json")
            status, content_type, body = result
            return _resp(status, body.encode(), content_type)
        return _resp(404, b"not found\n")

    async def handler(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request = await reader.readline()
            headers: Dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, sep, v = line.partition(b":")
                if sep:
                    headers[k.strip().decode("latin1").lower()] = \
                        v.strip().decode("latin1")
            parts = request.split()
            if len(parts) < 2:
                writer.write(_resp(400, b"bad request line\n"))
            else:
                method = parts[0].decode("latin1")
                target = parts[1].decode("latin1")
                path, _, query = target.partition("?")
                writer.write(await _route(method, path, _parse_qs(query),
                                          headers))
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(handler, host, port)
    health.register_liveness("loop-lag", _check_loop_lag)
    health.register_liveness("samplers", _check_samplers)
    # prune samplers from dead/foreign event loops (in-process restarts,
    # test suites) so the live loop gets its own set
    loop = asyncio.get_running_loop()
    _BACKGROUND_TASKS[:] = [t for t in _BACKGROUND_TASKS
                            if not t.done() and t.get_loop() is loop]
    if not _BACKGROUND_TASKS:  # exactly one sampler set per process
        _BACKGROUND_TASKS.append(asyncio.create_task(
            supervised(_running_latency_calculator, "running-latency"),
            name="metrics-running-latency"))
        _BACKGROUND_TASKS.append(asyncio.create_task(
            supervised(_loop_lag_sampler, "loop-lag"),
            name="metrics-loop-lag"))
        if profile_interval_s() > 0:
            _BACKGROUND_TASKS.append(asyncio.create_task(
                supervised(_task_profiler, "task-profiler"),
                name="metrics-task-profiler"))
    return server
