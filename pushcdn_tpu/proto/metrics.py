"""Metrics: Prometheus-style text exposition over HTTP + core gauges.

Capability parity with cdn-proto/src/metrics.rs:18-78 (warp `/metrics`
endpoint, 30 s running-latency gauge computed from histogram deltas) and
cdn-proto/src/connection/metrics.rs:12-28 (BYTES_SENT / BYTES_RECV gauges,
LATENCY histogram of permit-allocation lifetime).

Dependency-free: a tiny registry + asyncio HTTP server producing the
Prometheus text format. Metrics are always collected (cheap int adds); the
endpoint is opt-in per binary, matching the reference's `metrics` feature.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, List, Optional


class Counter:
    """Monotonic counter (exposed as prometheus counter)."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0
        _REGISTRY[name] = self

    def inc(self, n: int = 1) -> None:
        self.value += n

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self.value}\n")


class Gauge:
    """Settable gauge."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.value = 0.0
        _REGISTRY[name] = self

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n

    def dec(self, n: float = 1) -> None:
        self.value -= n

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} gauge\n"
                f"{self.name} {self.value}\n")


class Histogram:
    """Fixed-bucket histogram (seconds)."""

    DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

    def __init__(self, name: str, help_: str, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        _REGISTRY[name] = self

    def observe(self, v: float) -> None:
        self.sum += v
        self.total += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {self.total}')
        out.append(f"{self.name}_sum {self.sum}")
        out.append(f"{self.name}_count {self.total}")
        return "\n".join(out) + "\n"


_REGISTRY: Dict[str, object] = {}
_BACKGROUND_TASKS: List[asyncio.Task] = []  # keep refs so GC can't kill them

# Core connection metrics (parity connection/metrics.rs:13-28, incremented
# by the transport layer at frame write/read).
BYTES_SENT = Counter("cdn_bytes_sent", "Total bytes written to peers")
BYTES_RECV = Counter("cdn_bytes_received", "Total bytes read from peers")
LATENCY = Histogram("cdn_message_latency_seconds",
                    "Permit-allocation lifetime: receive -> last fan-out send")
RUNNING_LATENCY = Gauge("cdn_running_latency_seconds",
                        "30s running average message latency")


def observe_message_latency(seconds: float) -> None:
    LATENCY.observe(seconds)


# Cut-through routing plane (broker/tasks/cutthrough.py): one native plan
# call routes a whole FrameChunk without per-frame Python. The histogram
# buckets are FRAME COUNTS per plan call, not seconds.
ROUTE_BATCH_SIZE = Histogram(
    "cdn_route_batch_size_frames",
    "Frames covered by one cut-through route-plan call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
ROUTE_CUTTHROUGH_FRAMES = Counter(
    "cdn_route_batch_cutthrough_frames",
    "Frames routed by the native cut-through plan (no per-frame Python)")
ROUTE_RESIDUAL_FRAMES = Counter(
    "cdn_route_batch_residual_frames",
    "Frames the cut-through plane handed to the scalar path "
    "(control frames, malformed frames, depth-1 singles)")
ROUTE_SCALAR_FRAMES = Counter(
    "cdn_route_batch_scalar_frames",
    "Frames routed entirely by the scalar receive loops "
    "(cut-through off or ineligible)")
ROUTE_TABLE_REBUILDS = Counter(
    "cdn_route_table_rebuilds",
    "Cut-through snapshot rebuilds (routing state changed)")


# Callables run before every render: components whose counters move on
# hot paths (device-plane steps) register a refresh here instead of
# pushing gauge updates from their pump loops.
PRE_RENDER_HOOKS: list = []

# BLS per-public-key Miller line-table cache (native/bls_bn254.cpp): the
# auth hot path's amortization state. Gauges (not counters) because the
# native library owns the monotonic values and a cache clear legitimately
# zeroes them.
BLS_PK_CACHE_HITS = Gauge("cdn_bls_pk_cache_hits",
                          "BLS verify line-table cache hits")
BLS_PK_CACHE_MISSES = Gauge("cdn_bls_pk_cache_misses",
                            "BLS verify line-table cache misses")
BLS_PK_CACHE_EVICTIONS = Gauge("cdn_bls_pk_cache_evictions",
                               "BLS verify line-table LRU evictions")
BLS_PK_CACHE_ENTRIES = Gauge("cdn_bls_pk_cache_entries",
                             "BLS verify line tables currently cached")
BLS_PK_CACHE_BYTES = Gauge("cdn_bls_pk_cache_bytes",
                           "Resident bytes of cached BLS line tables")


def _refresh_bls_pk_cache() -> None:
    from pushcdn_tpu.native import bls
    # peek, never provoke: pk_cache_stats() would lazily COMPILE the
    # native library (a multi-second synchronous g++ run) and this hook
    # runs inside the asyncio /metrics handler — a process that never
    # verified a BLS signature keeps the gauges at zero instead
    if not bls.loaded():
        return
    stats = bls.pk_cache_stats()
    if stats is None:  # native library unavailable: gauges stay zero
        return
    BLS_PK_CACHE_HITS.set(stats["hits"])
    BLS_PK_CACHE_MISSES.set(stats["misses"])
    BLS_PK_CACHE_EVICTIONS.set(stats["evictions"])
    BLS_PK_CACHE_ENTRIES.set(stats["entries"])
    BLS_PK_CACHE_BYTES.set(stats["bytes"])


def register_bls_pk_cache_metrics() -> None:
    """Idempotent: pull the native cache counters into the gauges on
    every render. Registered by processes that actually verify BLS
    signatures (the marshal; brokers via their auth path) — a process
    that never loads the native library keeps the hook a no-op."""
    if _refresh_bls_pk_cache not in PRE_RENDER_HOOKS:
        PRE_RENDER_HOOKS.append(_refresh_bls_pk_cache)


_hook_failures: set = set()


def render_all() -> str:
    for hook in list(PRE_RENDER_HOOKS):
        try:
            hook()
        except Exception:
            # a broken hook must not take down /metrics, but a silently
            # frozen gauge is an operator trap — log each hook ONCE
            if id(hook) not in _hook_failures:
                _hook_failures.add(id(hook))
                logging.getLogger("pushcdn.metrics").exception(
                    "metrics pre-render hook %r failed; its gauges are "
                    "stale from here on", hook)
    return "".join(m.render() for m in _REGISTRY.values())


def render_tasks() -> str:
    """One line per live asyncio task: name, state, and where it is
    suspended — the poor man's tokio-console (`GET /tasks`)."""
    lines = []
    for task in sorted(asyncio.all_tasks(), key=lambda t: t.get_name()):
        # Task.cancelling is 3.11+; 3.10 images just report pending
        _cancelling = getattr(task, "cancelling", None)
        state = "done" if task.done() else (
            "cancelling" if _cancelling is not None and _cancelling()
            else "pending")
        where = ""
        if not task.done():
            stack = task.get_stack(limit=1)
            if stack:
                frame = stack[-1]
                where = f" @ {frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
        lines.append(f"{task.get_name()}  [{state}]{where}")
    return f"{len(lines)} tasks\n" + "\n".join(lines) + "\n"


async def _running_latency_calculator(interval_s: float = 30.0) -> None:
    """Recompute RUNNING_LATENCY from histogram deltas every ``interval_s``
    (parity metrics.rs:43-78)."""
    prev_sum, prev_total = LATENCY.sum, LATENCY.total
    while True:
        await asyncio.sleep(interval_s)
        ds, dn = LATENCY.sum - prev_sum, LATENCY.total - prev_total
        RUNNING_LATENCY.set(ds / dn if dn else 0.0)
        prev_sum, prev_total = LATENCY.sum, LATENCY.total


async def serve_metrics(bind_endpoint: str) -> asyncio.AbstractServer:
    """Serve ``GET /metrics`` as Prometheus text (parity metrics.rs:18-39).

    Returns the server; also spawns the running-latency calculator.
    """
    from pushcdn_tpu.proto.error import parse_endpoint
    host, port = parse_endpoint(bind_endpoint)

    async def handler(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass
            if b"/metrics" in request:
                body = render_all().encode()
                writer.write(b"HTTP/1.1 200 OK\r\nContent-Type: text/plain; version=0.0.4\r\n"
                             + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
            elif b"/tasks" in request:
                # async-runtime introspection (the reference wires
                # tokio-console behind tokio_unstable; here a plain dump of
                # every live asyncio task: name, state, current frame)
                body = render_tasks().encode()
                writer.write(b"HTTP/1.1 200 OK\r\nContent-Type: text/plain\r\n"
                             + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(handler, host, port)
    if not _BACKGROUND_TASKS:  # exactly one calculator per process
        _BACKGROUND_TASKS.append(asyncio.create_task(_running_latency_calculator()))
    return server
