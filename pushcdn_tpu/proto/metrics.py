"""Metrics: Prometheus-style text exposition over HTTP + core gauges.

Capability parity with cdn-proto/src/metrics.rs:18-78 (warp `/metrics`
endpoint, 30 s running-latency gauge computed from histogram deltas) and
cdn-proto/src/connection/metrics.rs:12-28 (BYTES_SENT / BYTES_RECV gauges,
LATENCY histogram of permit-allocation lifetime).

Dependency-free: a tiny registry + asyncio HTTP server producing the
Prometheus text format. Metrics are always collected (cheap int adds); the
endpoint is opt-in per binary, matching the reference's `metrics` feature.

Label support (ISSUE 4 registry upgrade): every metric type takes an
optional ``labels=(...)`` tuple of label NAMES; ``m.labels(name=value)``
returns (creating on first use) a child series that renders as
``name{label="value"} v`` and exposes the same mutator API — call sites
hold the child and pay a plain attribute call per update, exactly like
before. A labeled Counter also renders a bare total line (own value + the
children's sum) so pre-label dashboards keep working.

Thread-safety: mutators (``inc``/``set``/``observe``) and child creation
take one process-wide lock — native-code callers and bench threads observe
from off-loop threads, and an unlocked ``Histogram.observe`` loses updates
in its sum/bucket read-modify-write. The lock is uncontended in steady
state (hot paths update per *batch*, not per frame) and a render takes it
per-metric, so a scrape racing live updates sees each metric atomically.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import threading
from typing import Dict, List

_LOCK = threading.Lock()


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


class _LabeledMixin:
    """Shared child-series machinery. ``self._label_names`` is the declared
    label-name tuple (empty = unlabeled); ``self._labels`` is this series'
    own rendered ``k="v"`` pair string (children only)."""

    def _init_labels(self, labels) -> None:
        self._label_names = tuple(labels)
        self._labels = ""
        self._children: Dict[tuple, "_LabeledMixin"] = {}

    def labels(self, **kv):
        """The child series for these label values (create on first use).
        Raises ``KeyError`` on a label name that was not declared."""
        try:
            key = tuple(str(kv[n]) for n in self._label_names)
        except KeyError:
            raise KeyError(f"{self.name}: labels() requires exactly "
                           f"{self._label_names}, got {tuple(kv)}") from None
        if len(kv) != len(self._label_names):
            raise KeyError(f"{self.name}: labels() requires exactly "
                           f"{self._label_names}, got {tuple(kv)}")
        child = self._children.get(key)
        if child is None:
            with _LOCK:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    child._labels = ",".join(
                        f'{n}="{_escape_label(v)}"'
                        for n, v in zip(self._label_names, key))
                    self._children[key] = child
        return child

    def _sorted_children(self):
        return [self._children[k] for k in sorted(self._children)]


class Counter(_LabeledMixin):
    """Monotonic counter (exposed as prometheus counter)."""

    def __init__(self, name: str, help_: str, labels=()):
        self.name = name
        self.help = help_
        self.value = 0
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Counter":
        child = Counter.__new__(Counter)
        child.name, child.help, child.value = self.name, self.help, 0
        child._init_labels(())
        return child

    def inc(self, n: int = 1) -> None:
        with _LOCK:
            self.value += n

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with _LOCK:
            total = self.value
            for child in self._sorted_children():
                total += child.value
                out.append(f"{self.name}{{{child._labels}}} {child.value}")
            out.append(f"{self.name} {total}")
        return "\n".join(out) + "\n"


class Gauge(_LabeledMixin):
    """Settable gauge."""

    def __init__(self, name: str, help_: str, labels=()):
        self.name = name
        self.help = help_
        self.value = 0.0
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Gauge":
        child = Gauge.__new__(Gauge)
        child.name, child.help, child.value = self.name, self.help, 0.0
        child._init_labels(())
        return child

    def set(self, v: float) -> None:
        with _LOCK:
            self.value = v

    def inc(self, n: float = 1) -> None:
        with _LOCK:
            self.value += n

    def dec(self, n: float = 1) -> None:
        with _LOCK:
            self.value -= n

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with _LOCK:
            for child in self._sorted_children():
                out.append(f"{self.name}{{{child._labels}}} {child.value}")
            if not self._label_names:
                out.append(f"{self.name} {self.value}")
            elif not self._children:
                # labeled gauge with no series yet: render nothing (a bare
                # 0 under set-semantics would be a lie)
                pass
        return "\n".join(out) + "\n"


class Histogram(_LabeledMixin):
    """Fixed-bucket histogram (seconds)."""

    DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

    def __init__(self, name: str, help_: str, buckets=DEFAULT_BUCKETS,
                 labels=()):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        self._init_labels(labels)
        _REGISTRY[name] = self

    def _new_child(self) -> "Histogram":
        child = Histogram.__new__(Histogram)
        child.name, child.help = self.name, self.help
        child.buckets = self.buckets
        child.counts = [0] * (len(self.buckets) + 1)
        child.sum = 0.0
        child.total = 0
        child._init_labels(())
        return child

    def observe(self, v: float) -> None:
        # The whole update is one critical section: sum/total/bucket are a
        # multi-step read-modify-write, and off-loop observers (native-code
        # callers, bench threads) would otherwise lose samples against the
        # event loop's updates.
        with _LOCK:
            self.sum += v
            self.total += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def _render_series(self, out: List[str], labels: str) -> None:
        sep = f"{labels}," if labels else ""
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{self.name}_bucket{{{sep}le="{b}"}} {cum}')
        out.append(f'{self.name}_bucket{{{sep}le="+Inf"}} {self.total}')
        tail = f"{{{labels}}}" if labels else ""
        out.append(f"{self.name}_sum{tail} {self.sum}")
        out.append(f"{self.name}_count{tail} {self.total}")

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with _LOCK:
            for child in self._sorted_children():
                child._render_series(out, child._labels)
            if not self._label_names:
                self._render_series(out, "")
        return "\n".join(out) + "\n"


_REGISTRY: Dict[str, object] = {}
_BACKGROUND_TASKS: List[asyncio.Task] = []  # keep refs so GC can't kill them

# Core connection metrics (parity connection/metrics.rs:13-28, incremented
# by the transport layer at frame write/read). Labeled per transport — the
# connection caches its child at construction, so the hot path still pays
# one plain ``inc`` per flush.
BYTES_SENT = Counter("cdn_bytes_sent", "Total bytes written to peers",
                     labels=("transport",))
BYTES_RECV = Counter("cdn_bytes_received", "Total bytes read from peers",
                     labels=("transport",))
LATENCY = Histogram("cdn_message_latency_seconds",
                    "Permit-allocation lifetime: receive -> last fan-out send")
RUNNING_LATENCY = Gauge("cdn_running_latency_seconds",
                        "30s running average message latency")


def observe_message_latency(seconds: float) -> None:
    LATENCY.observe(seconds)


# Cut-through routing plane (broker/tasks/cutthrough.py): one native plan
# call routes a whole FrameChunk without per-frame Python. The histogram
# buckets are FRAME COUNTS per plan call, not seconds. The three per-path
# frame counters are one labeled family; the module attributes below are
# the cached children, so call sites stay `ROUTE_*_FRAMES.inc(n)`.
ROUTE_BATCH_SIZE = Histogram(
    "cdn_route_batch_size_frames",
    "Frames covered by one cut-through route-plan call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
ROUTE_FRAMES = Counter(
    "cdn_route_batch_frames",
    "Frames routed, by path: cutthrough = native plan (no per-frame "
    "Python), residual = handed to the scalar path by the plan (control/"
    "traced/malformed frames, depth-1 singles), scalar = routed entirely "
    "by the scalar receive loops",
    labels=("path",))
ROUTE_CUTTHROUGH_FRAMES = ROUTE_FRAMES.labels(path="cutthrough")
ROUTE_RESIDUAL_FRAMES = ROUTE_FRAMES.labels(path="residual")
ROUTE_SCALAR_FRAMES = ROUTE_FRAMES.labels(path="scalar")
ROUTE_TABLE_REBUILDS = Counter(
    "cdn_route_table_rebuilds",
    "Cut-through snapshot rebuilds (routing state changed)")

# Egress fan-out accounting by peer type (EgressBatch.flush / the
# cut-through _send_plan increment batch-wise).
EGRESS_FRAMES = Counter(
    "cdn_egress_frames",
    "Frames handed to connection writers, by destination peer type",
    labels=("peer",))
EGRESS_FRAMES_USER = EGRESS_FRAMES.labels(peer="user")
EGRESS_FRAMES_BROKER = EGRESS_FRAMES.labels(peer="broker")

# Writer-queue depth across live connections (refreshed at render by a
# pre-render hook over the transport layer's connection registry) and
# event-loop lag (sampled by a supervised background task).
WRITER_QUEUE_DEPTH = Gauge(
    "cdn_writer_queue_depth",
    "Entries waiting in connection send queues (stat=sum|max across "
    "live connections)",
    labels=("stat",))
EVENT_LOOP_LAG = Gauge(
    "cdn_event_loop_lag_seconds",
    "How late the event loop ran a sleep(0.25) wakeup (scheduling lag)")

# Global memory-pool occupancy (refreshed at render from the limiter's
# live-pool registry).
POOL_BYTES = Gauge(
    "cdn_pool_bytes",
    "Global byte-pool permit accounting across live pools "
    "(state=in_use|capacity)",
    labels=("state",))


# Callables run before every render: components whose counters move on
# hot paths (device-plane steps) register a refresh here instead of
# pushing gauge updates from their pump loops.
PRE_RENDER_HOOKS: list = []

# BLS per-public-key Miller line-table cache (native/bls_bn254.cpp): the
# auth hot path's amortization state. One labeled gauge family (not
# counters, because the native library owns the monotonic values and a
# cache clear legitimately zeroes them); module attributes are the cached
# children so existing call sites keep working.
BLS_PK_CACHE = Gauge("cdn_bls_pk_cache",
                     "BLS verify line-table cache state "
                     "(stat=hits|misses|evictions|entries|bytes)",
                     labels=("stat",))
BLS_PK_CACHE_HITS = BLS_PK_CACHE.labels(stat="hits")
BLS_PK_CACHE_MISSES = BLS_PK_CACHE.labels(stat="misses")
BLS_PK_CACHE_EVICTIONS = BLS_PK_CACHE.labels(stat="evictions")
BLS_PK_CACHE_ENTRIES = BLS_PK_CACHE.labels(stat="entries")
BLS_PK_CACHE_BYTES = BLS_PK_CACHE.labels(stat="bytes")

# Message-lifecycle tracing (proto/trace.py): per-hop latency from the
# traced message's origin. Defined here (not in trace.py) so every
# /metrics endpoint exposes the family even before the first span.
TRACE_HOP_LATENCY = Histogram(
    "cdn_trace_hop_seconds",
    "Time from a traced message's origin to each lifecycle hop "
    "(hop=publish|auth|ingress|plan|egress|delivery)",
    labels=("hop",))

# Build/runtime identity: one constant-1 series whose labels carry the
# package version, jax version, and the ACTUAL backend/device kind —
# so "ALIVE but device_kind=cpu" (TPU_PROBES r5/r6) is visible on every
# scrape instead of buried in a probes file.
BUILD_INFO = Gauge("cdn_build_info",
                   "Build/runtime identity (value is always 1)",
                   labels=("version", "jax", "backend", "device_kind"))


_build_info_last: tuple = ()


def _refresh_build_info() -> None:
    """(Re)probe cdn_build_info at every render — the backend can
    initialize AFTER the first scrape (a broker attaches its device plane
    lazily), and a frozen 'uninitialized' label would defeat the point.
    The stale series drops to 0 and the current one reads 1. Never
    *initializes* jax: a broker that never touched an accelerator must
    not pay a multi-second backend probe inside its /metrics handler —
    unimported jax reports backend=unloaded, imported-but-uninitialized
    reports uninitialized (jax.devices() on an already-initialized
    backend is a cached lookup)."""
    global _build_info_last
    import pushcdn_tpu
    jax_mod = sys.modules.get("jax")
    jax_v = getattr(jax_mod, "__version__", "absent") if jax_mod else "absent"
    backend = "unloaded"
    device_kind = "unknown"
    if jax_mod is not None:
        try:
            # peek, never provoke: only report devices when a backend has
            # already been initialized by the process' own work
            backends = getattr(
                sys.modules.get("jax._src.xla_bridge"), "_backends", None)
            if backends:
                dev = jax_mod.devices()[0]
                backend = dev.platform
                device_kind = dev.device_kind
            else:
                backend = "uninitialized"
        except Exception:
            backend = "error"
    current = (pushcdn_tpu.__version__, jax_v, backend, device_kind)
    if current == _build_info_last:
        return
    if _build_info_last:
        BUILD_INFO.labels(version=_build_info_last[0], jax=_build_info_last[1],
                          backend=_build_info_last[2],
                          device_kind=_build_info_last[3]).set(0)
    BUILD_INFO.labels(version=current[0], jax=current[1], backend=current[2],
                      device_kind=current[3]).set(1)
    _build_info_last = current


def _refresh_bls_pk_cache() -> None:
    from pushcdn_tpu.native import bls
    # peek, never provoke: pk_cache_stats() would lazily COMPILE the
    # native library (a multi-second synchronous g++ run) and this hook
    # runs inside the asyncio /metrics handler — a process that never
    # verified a BLS signature keeps the gauges at zero instead
    if not bls.loaded():
        return
    stats = bls.pk_cache_stats()
    if stats is None:  # native library unavailable: gauges stay zero
        return
    BLS_PK_CACHE_HITS.set(stats["hits"])
    BLS_PK_CACHE_MISSES.set(stats["misses"])
    BLS_PK_CACHE_EVICTIONS.set(stats["evictions"])
    BLS_PK_CACHE_ENTRIES.set(stats["entries"])
    BLS_PK_CACHE_BYTES.set(stats["bytes"])


def register_bls_pk_cache_metrics() -> None:
    """Idempotent: pull the native cache counters into the gauges on
    every render. Registered by processes that actually verify BLS
    signatures (the marshal; brokers via their auth path) — a process
    that never loads the native library keeps the hook a no-op."""
    if _refresh_bls_pk_cache not in PRE_RENDER_HOOKS:
        PRE_RENDER_HOOKS.append(_refresh_bls_pk_cache)


def _refresh_writer_queues() -> None:
    """Sum/max of send-queue depths across live connections (the transport
    layer keeps a weak registry). Lazy module lookup: a process that never
    created a connection reports zeros without importing the transport."""
    base = sys.modules.get("pushcdn_tpu.proto.transport.base")
    total = depth_max = 0
    if base is not None:
        for conn in list(base.LIVE_CONNECTIONS):
            try:
                d = conn._send_q.qsize()
            except Exception:
                continue
            total += d
            if d > depth_max:
                depth_max = d
    WRITER_QUEUE_DEPTH.labels(stat="sum").set(total)
    WRITER_QUEUE_DEPTH.labels(stat="max").set(depth_max)


def _refresh_pools() -> None:
    """Global byte-pool occupancy across live pools (limiter registry)."""
    limiter_mod = sys.modules.get("pushcdn_tpu.proto.limiter")
    in_use = capacity = 0
    if limiter_mod is not None:
        for pool in list(limiter_mod.LIVE_POOLS):
            capacity += pool.capacity
            in_use += pool.capacity - pool.available
    POOL_BYTES.labels(state="in_use").set(in_use)
    POOL_BYTES.labels(state="capacity").set(capacity)


PRE_RENDER_HOOKS.append(_refresh_build_info)
PRE_RENDER_HOOKS.append(_refresh_writer_queues)
PRE_RENDER_HOOKS.append(_refresh_pools)


_hook_failures: set = set()


def render_all() -> str:
    for hook in list(PRE_RENDER_HOOKS):
        try:
            hook()
        except Exception:
            # a broken hook must not take down /metrics, but a silently
            # frozen gauge is an operator trap — log each hook ONCE
            if id(hook) not in _hook_failures:
                _hook_failures.add(id(hook))
                logging.getLogger("pushcdn.metrics").exception(
                    "metrics pre-render hook %r failed; its gauges are "
                    "stale from here on", hook)
    return "".join(m.render() for m in list(_REGISTRY.values()))


def render_tasks() -> str:
    """One line per live asyncio task: name, state, and where it is
    suspended — the poor man's tokio-console (`GET /tasks`)."""
    lines = []
    for task in sorted(asyncio.all_tasks(), key=lambda t: t.get_name()):
        # Task.cancelling is 3.11+; 3.10 images just report pending
        _cancelling = getattr(task, "cancelling", None)
        state = "done" if task.done() else (
            "cancelling" if _cancelling is not None and _cancelling()
            else "pending")
        where = ""
        if not task.done():
            stack = task.get_stack(limit=1)
            if stack:
                frame = stack[-1]
                where = f" @ {frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
        lines.append(f"{task.get_name()}  [{state}]{where}")
    return f"{len(lines)} tasks\n" + "\n".join(lines) + "\n"


def supervised(factory, name: str, restart_delay_s: float = 1.0):
    """Run ``await factory()`` forever, logging + restarting on exception
    instead of letting the task die silently for the rest of the process
    lifetime (the pre-ISSUE-4 fate of ``_running_latency_calculator``).
    Each death is recorded in the process flight recorder so the trail
    shows up in ``/debug/flightrec`` and the diagnostics log."""
    from pushcdn_tpu.proto import flightrec

    async def _runner():
        rec = flightrec.task_recorder()
        while True:
            try:
                await factory()
                rec.record("task-exited", name)
                logging.getLogger("pushcdn.metrics").warning(
                    "supervised task %r returned; restarting", name)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                rec.record("task-died", f"{name}: {exc!r}", abnormal=True)
                logging.getLogger("pushcdn.metrics").exception(
                    "supervised task %r died; restarting in %.1fs",
                    name, restart_delay_s)
            await asyncio.sleep(restart_delay_s)

    return _runner()


async def _running_latency_calculator(interval_s: float = 30.0) -> None:
    """Recompute RUNNING_LATENCY from histogram deltas every ``interval_s``
    (parity metrics.rs:43-78)."""
    prev_sum, prev_total = LATENCY.sum, LATENCY.total
    while True:
        await asyncio.sleep(interval_s)
        ds, dn = LATENCY.sum - prev_sum, LATENCY.total - prev_total
        RUNNING_LATENCY.set(ds / dn if dn else 0.0)
        prev_sum, prev_total = LATENCY.sum, LATENCY.total


_loop_lag_peak = 0.0


def _refresh_loop_lag() -> None:
    """Publish the PEAK lag since the last scrape, then reset. A plain
    last-sample gauge would be overwritten by the next on-time wakeup
    ~interval later, hiding every stall shorter than the scrape interval
    — exactly the incidents the metric exists to surface."""
    global _loop_lag_peak
    EVENT_LOOP_LAG.set(_loop_lag_peak)
    _loop_lag_peak = 0.0


PRE_RENDER_HOOKS.append(_refresh_loop_lag)


async def _loop_lag_sampler(interval_s: float = 0.25) -> None:
    """Sample event-loop scheduling lag: how late a sleep() wakeup ran.
    A loop hogged by a long synchronous section (native call, giant
    decode) shows up here before it shows up as user-visible latency.
    Samples accumulate as a max; the pre-render hook publishes-and-resets
    per scrape."""
    global _loop_lag_peak
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(interval_s)
        lag = loop.time() - t0 - interval_s
        if lag > _loop_lag_peak:
            _loop_lag_peak = lag


async def serve_metrics(bind_endpoint: str) -> asyncio.AbstractServer:
    """Serve ``GET /metrics`` as Prometheus text (parity metrics.rs:18-39),
    ``GET /tasks`` (asyncio task dump) and ``GET /debug/flightrec`` (every
    live flight recorder's trail).

    Returns the server; also spawns the supervised background samplers
    (running-latency calculator, event-loop-lag sampler).
    """
    from pushcdn_tpu.proto import flightrec
    from pushcdn_tpu.proto.error import parse_endpoint
    host, port = parse_endpoint(bind_endpoint)

    def _plain(body: bytes, content_type: bytes = b"text/plain") -> bytes:
        return (b"HTTP/1.1 200 OK\r\nContent-Type: " + content_type
                + f"\r\nContent-Length: {len(body)}\r\n\r\n".encode() + body)

    async def handler(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass
            if b"/debug/flightrec" in request:
                writer.write(_plain(flightrec.render_all().encode()))
            elif b"/metrics" in request:
                writer.write(_plain(
                    render_all().encode(),
                    b"text/plain; version=0.0.4"))
            elif b"/tasks" in request:
                # async-runtime introspection (the reference wires
                # tokio-console behind tokio_unstable; here a plain dump of
                # every live asyncio task: name, state, current frame)
                writer.write(_plain(render_tasks().encode()))
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(handler, host, port)
    if not _BACKGROUND_TASKS:  # exactly one sampler set per process
        _BACKGROUND_TASKS.append(asyncio.create_task(
            supervised(_running_latency_calculator, "running-latency"),
            name="metrics-running-latency"))
        _BACKGROUND_TASKS.append(asyncio.create_task(
            supervised(_loop_lag_sampler, "loop-lag"),
            name="metrics-loop-lag"))
    return server
