"""Authentication flows (reference layer L4, cdn-proto/src/connection/auth/).

Three parties, three modules:

- ``user``    — the client side: marshal handshake then broker handshake
- ``marshal`` — verify a user, pick a broker, issue a permit
- ``broker``  — redeem user permits; mutual broker↔broker auth
"""
