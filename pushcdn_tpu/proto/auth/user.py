"""User-side authentication.

Capability parity with cdn-proto/src/connection/auth/user.rs:28-162:

1. ``authenticate_with_marshal`` — sign the current unix timestamp under the
   ``USER_MARSHAL_AUTH`` namespace, send ``AuthenticateWithKey``, receive
   ``AuthenticateResponse`` carrying ``(permit, broker_endpoint)``
   (user.rs:50-86).
2. ``authenticate_with_broker`` — redeem the permit at that broker, await
   the ack, then send the ``Subscribe`` topic list so subscriptions survive
   reconnects (user.rs:108-161).
"""

from __future__ import annotations

import asyncio
import struct
import time
from typing import List, Tuple, Type

from pushcdn_tpu.proto.crypto.signature import KeyPair, Namespace, SignatureScheme
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    AuthenticateWithKey,
    AuthenticateWithPermit,
    Subscribe,
)
from pushcdn_tpu.proto.transport.base import Connection

_TS = struct.Struct("<Q")


def signable_timestamp(timestamp: int) -> bytes:
    return _TS.pack(timestamp)


def presign_timestamp(scheme: Type[SignatureScheme],
                      keypair: KeyPair) -> Tuple[int, bytes]:
    """Sign the current timestamp for marshal auth ahead of time — the
    caller can run this CPU work while the TCP dial is in flight and pass
    the result to :func:`authenticate_with_marshal`. The ±5 s replay
    window dwarfs any sane connect time, so signing before the socket
    exists is safe."""
    timestamp = int(time.time())
    return timestamp, scheme.sign(keypair.private_key,
                                  Namespace.USER_MARSHAL_AUTH,
                                  signable_timestamp(timestamp))


async def authenticate_with_marshal(
        connection: Connection, scheme: Type[SignatureScheme],
        keypair: KeyPair,
        presigned: Tuple[int, bytes] | None = None,
        trace=None) -> Tuple[int, str]:
    """Returns ``(permit, broker_public_endpoint)`` or raises
    ``Error(AUTHENTICATION)``. ``presigned`` is an optional
    :func:`presign_timestamp` result computed while the dial was in
    flight (the connect-latency overlap). ``trace`` is an optional
    lifecycle-trace context ``(trace_id, origin_ns)``: the auth frame is
    stamped with it (kind-tag flag bit + 16-byte block) so the marshal
    emits the ``auth`` span on the same trace id the client's first
    published message will carry."""
    from pushcdn_tpu.proto import trace as trace_mod
    from pushcdn_tpu.proto.message import serialize
    timestamp, signature = (presigned if presigned is not None
                            else presign_timestamp(scheme, keypair))
    frame = serialize(AuthenticateWithKey(
        public_key=keypair.public_key, timestamp=timestamp,
        signature=signature))
    if trace is not None:
        frame = trace_mod.stamp_frame(frame, trace)
    await connection.send_raw(frame, flush=True)

    response = await connection.recv_message()
    if not isinstance(response, AuthenticateResponse):
        bail(ErrorKind.AUTHENTICATION,
             f"marshal sent unexpected {type(response).__name__}")
    if response.permit <= 1:
        # permit 0 = failure with reason; 1 would be a bare ack which the
        # marshal never sends (message.rs:338-341 semantics)
        _bail_rejection("marshal", response.context)
    return response.permit, response.context


def _bail_rejection(who: str, context: str):
    """A ``permit=0`` rejection at connect time: load sheds surface as the
    TYPED ``Error(SHED)`` (carrying any ``retry-after=`` hint for the
    client's backoff loop, ISSUE 12) so they're distinguishable from a
    real auth failure — today both looked identical to the retry logic."""
    if context.startswith("shed"):
        bail(ErrorKind.SHED, f"{who} shed the connection: {context}")
    bail(ErrorKind.AUTHENTICATION,
         f"{who} rejected authentication: {context!r}")


async def authenticate_with_broker(
        connection: Connection, permit: int, topics: List[int]) -> None:
    """Redeem ``permit`` and replay our subscription set (user.rs:108-161).

    The wire sequence is the reference's (permit, ack, Subscribe) but the
    client PIPELINES: permit and Subscribe go out in one flush, then the
    ack is awaited. The broker reads them in order either way (it
    validates the permit before touching the Subscribe), an invalid
    permit still tears the connection down before the Subscribe is acted
    on, and the handshake drops one full round trip."""
    # both flushed: back-to-back flushed sends on an idle link take the
    # transport's inline fast path (no writer-task spawn for the whole
    # handshake), and the broker still reads them in order
    await connection.send_message(AuthenticateWithPermit(permit=permit),
                                  flush=True)
    try:
        await connection.send_message(Subscribe(topics), flush=True)
    except Error as send_err:
        # A rejected permit tears the connection down broker-side, so the
        # pipelined Subscribe's flush can fail before we ever read the
        # response — but the rejection (permit 0 + reason) is usually
        # already buffered ahead of the FIN. Surface THAT instead of a
        # generic write error; fall back to the send error when no
        # response is readable.
        try:
            async with asyncio.timeout(5.0):
                response = await connection.recv_message()
        except Exception:
            raise send_err
        if isinstance(response, AuthenticateResponse) and response.permit != 1:
            _bail_rejection("broker", response.context)
        raise send_err
    response = await connection.recv_message()
    if not isinstance(response, AuthenticateResponse):
        bail(ErrorKind.AUTHENTICATION,
             f"broker sent unexpected {type(response).__name__}")
    if response.permit != 1:
        _bail_rejection("broker", response.context)
