"""Marshal-side user verification.

Capability parity with cdn-proto/src/connection/auth/marshal.rs:34-148:
verify the signed timestamp (±5 s replay window, marshal.rs:76-83), check
the whitelist, pick the least-loaded broker, issue a 30-second single-use
permit (marshal.rs:105-141), reply ``(permit, broker_public_endpoint)``.
Failures are reported to the user as ``AuthenticateResponse(permit=0,
context=reason)`` before bailing.
"""

from __future__ import annotations

import time
from typing import Tuple, Type

from pushcdn_tpu.proto import trace as trace_mod
from pushcdn_tpu.proto.auth.user import signable_timestamp
from pushcdn_tpu.proto.crypto.signature import Namespace, SignatureScheme
from pushcdn_tpu.proto.discovery.base import DiscoveryClient
from pushcdn_tpu.proto.error import ErrorKind, bail
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    AuthenticateWithKey,
    deserialize_owned,
)
from pushcdn_tpu.proto.transport.base import Connection

# parity constants (marshal.rs:76-83, :121-135)
TIMESTAMP_TOLERANCE_S = 5
PERMIT_EXPIRY_S = 30.0


async def _reject(connection: Connection, reason: str):
    try:
        await connection.send_message(
            AuthenticateResponse(permit=0, context=reason), flush=True)
    except Exception:
        pass
    bail(ErrorKind.AUTHENTICATION, reason)


async def verify_user(connection: Connection, discovery: DiscoveryClient,
                      scheme: Type[SignatureScheme],
                      verifier=None) -> Tuple[bytes, int]:
    """Run the marshal side of the handshake on one fresh connection.

    Returns ``(user_public_key, permit)`` after replying with the permit and
    the chosen broker's public endpoint. ``verifier`` (an optional
    crypto.batch.BatchVerifier) amortizes concurrent pairing checks under
    connection storms; semantics are identical to ``scheme.verify``.
    """
    # Frame-level trace strip: a client that sampled this connection set
    # the kind-tag trace flag on its auth frame (proto.trace); the span is
    # emitted after a SUCCESSFUL verification, so the auth hop measures
    # dial + handshake + verify from the client's dial-time origin.
    raw = await connection.recv_raw()
    try:
        frame, auth_trace = trace_mod.strip_frame(raw.data)
    finally:
        raw.release()
    message = deserialize_owned(frame)
    if not isinstance(message, AuthenticateWithKey):
        await _reject(connection, "expected AuthenticateWithKey")

    # signature over the timestamp, namespaced (marshal.rs:66-83)
    if verifier is not None:
        sig_ok = await verifier.verify(
            message.public_key, Namespace.USER_MARSHAL_AUTH,
            signable_timestamp(message.timestamp), message.signature)
    else:
        sig_ok = scheme.verify(message.public_key,
                               Namespace.USER_MARSHAL_AUTH,
                               signable_timestamp(message.timestamp),
                               message.signature)
    if not sig_ok:
        await _reject(connection, "invalid signature")
    if abs(int(time.time()) - message.timestamp) > TIMESTAMP_TOLERANCE_S:
        await _reject(connection, "timestamp too old")

    # whitelist (marshal.rs:91-105)
    if not await discovery.check_whitelist(message.public_key):
        await _reject(connection, "not in whitelist")

    # least-loaded broker (marshal.rs:109-118)
    try:
        broker = await discovery.get_with_least_connections()
    except Exception:
        await _reject(connection, "no brokers available")

    # 30 s single-use permit (marshal.rs:121-135)
    permit = await discovery.issue_permit(broker, PERMIT_EXPIRY_S,
                                          message.public_key)
    await connection.send_message(
        AuthenticateResponse(permit=permit,
                             context=broker.public_advertise_endpoint),
        flush=True)
    connection.flightrec.record("auth-ok")
    if auth_trace is not None:
        trace_mod.emit("auth", auth_trace, "marshal-verify")
    return message.public_key, permit
