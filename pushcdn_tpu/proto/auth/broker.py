"""Broker-side authentication: user permit redemption + mutual
broker↔broker verification.

Capability parity with cdn-proto/src/connection/auth/broker.rs:36-301:

- ``verify_user`` (broker.rs:77-151): receive ``AuthenticateWithPermit``,
  redeem it against discovery (GETDEL semantics), ack, then receive the
  user's ``Subscribe`` topics.
- Broker↔broker auth (broker.rs:160-300): a mutual signed-timestamp
  exchange where both sides must hold the **same** broker keypair (the
  same-key check at broker.rs:286-288 — one deployment, one broker key).
  Direction fixes the order (the reference's ``authenticate_with_broker!``
  / ``verify_broker!`` macros): the *dialing* side authenticates first,
  the *accepting* side verifies first, so the two halves interleave without
  deadlock.

Wire note: ``AuthenticateWithKey.public_key`` is an opaque byte field; for
broker↔broker auth it carries ``u16 key_len || raw_public_key || identity_utf8``
so the peer learns which broker connected (the length prefix keeps the
split scheme-agnostic: Ed25519 keys are 32 B, BLS-BN254 keys 128 B), and
the signature covers ``timestamp || identity`` to bind the claimed
identity.
"""

from __future__ import annotations

import struct
import time
from typing import List, Tuple, Type

from pushcdn_tpu.proto.crypto.signature import KeyPair, Namespace, SignatureScheme
from pushcdn_tpu.proto.discovery.base import BrokerIdentifier, DiscoveryClient
from pushcdn_tpu.proto.error import ErrorKind, bail
from pushcdn_tpu.proto.message import (
    AuthenticateResponse,
    AuthenticateWithKey,
    AuthenticateWithPermit,
    Subscribe,
)
from pushcdn_tpu.proto.transport.base import Connection

_TS = struct.Struct("<Q")
_KEY_LEN = struct.Struct("<H")  # scheme-agnostic key-length prefix

TIMESTAMP_TOLERANCE_S = 5


# ---------------------------------------------------------------------------
# users
# ---------------------------------------------------------------------------

async def verify_user(connection: Connection, discovery: DiscoveryClient,
                      identity: BrokerIdentifier
                      ) -> Tuple[bytes, List[int]]:
    """Redeem a user's permit; returns ``(public_key, topics)``
    (broker.rs:77-151)."""
    message = await connection.recv_message()
    if not isinstance(message, AuthenticateWithPermit):
        bail(ErrorKind.AUTHENTICATION, "expected AuthenticateWithPermit")

    public_key = await discovery.validate_permit(identity, message.permit)
    if public_key is None:
        try:
            await connection.send_message(
                AuthenticateResponse(permit=0, context="invalid permit"),
                flush=True)
        except Exception:
            pass
        bail(ErrorKind.AUTHENTICATION, "invalid permit")

    await connection.send_message(AuthenticateResponse(permit=1, context=""),
                                  flush=True)

    # The user follows the ack with its Subscribe set (broker.rs:119-150).
    sub = await connection.recv_message()
    if not isinstance(sub, Subscribe):
        bail(ErrorKind.AUTHENTICATION, "expected Subscribe after permit ack")
    return public_key, list(sub.topics)


# ---------------------------------------------------------------------------
# brokers
# ---------------------------------------------------------------------------

def _broker_signable(timestamp: int, identity: str) -> bytes:
    return _TS.pack(timestamp) + identity.encode("utf-8")


async def _send_auth(connection: Connection, scheme: Type[SignatureScheme],
                     keypair: KeyPair, identity: BrokerIdentifier) -> None:
    timestamp = int(time.time())
    ident = str(identity)
    signature = scheme.sign(keypair.private_key, Namespace.BROKER_BROKER_AUTH,
                            _broker_signable(timestamp, ident))
    await connection.send_message(AuthenticateWithKey(
        public_key=_KEY_LEN.pack(len(keypair.public_key))
        + keypair.public_key + ident.encode("utf-8"),
        timestamp=timestamp, signature=signature), flush=True)
    response = await connection.recv_message()
    if not isinstance(response, AuthenticateResponse) or response.permit != 1:
        bail(ErrorKind.AUTHENTICATION, "peer broker rejected our auth")


async def _recv_auth(connection: Connection, scheme: Type[SignatureScheme],
                     keypair: KeyPair) -> BrokerIdentifier:
    message = await connection.recv_message()
    if not isinstance(message, AuthenticateWithKey):
        bail(ErrorKind.AUTHENTICATION, "expected broker AuthenticateWithKey")
    packed = bytes(message.public_key)
    if len(packed) < _KEY_LEN.size:
        await _reject(connection, "malformed broker key field")
    (key_len,) = _KEY_LEN.unpack_from(packed)
    if len(packed) < _KEY_LEN.size + key_len:
        await _reject(connection, "malformed broker key field")
    raw_key = packed[_KEY_LEN.size:_KEY_LEN.size + key_len]
    ident = packed[_KEY_LEN.size + key_len:].decode("utf-8", "replace")
    # Same-key check: peer must hold OUR broker keypair (broker.rs:286-288).
    if raw_key != keypair.public_key:
        await _reject(connection, "broker key mismatch")
    if not scheme.verify(raw_key, Namespace.BROKER_BROKER_AUTH,
                         _broker_signable(message.timestamp, ident),
                         message.signature):
        await _reject(connection, "invalid broker signature")
    if abs(int(time.time()) - message.timestamp) > TIMESTAMP_TOLERANCE_S:
        await _reject(connection, "broker timestamp too old")
    await connection.send_message(AuthenticateResponse(permit=1, context=""),
                                  flush=True)
    return BrokerIdentifier.from_string(ident)


async def _reject(connection: Connection, reason: str):
    try:
        await connection.send_message(
            AuthenticateResponse(permit=0, context=reason), flush=True)
    except Exception:
        pass
    bail(ErrorKind.AUTHENTICATION, reason)


async def authenticate_as_dialer(connection: Connection,
                                 scheme: Type[SignatureScheme],
                                 keypair: KeyPair,
                                 identity: BrokerIdentifier
                                 ) -> BrokerIdentifier:
    """Outbound side: authenticate first, then verify the peer
    (the direction ordering of broker.rs:160-236)."""
    await _send_auth(connection, scheme, keypair, identity)
    return await _recv_auth(connection, scheme, keypair)


async def authenticate_as_listener(connection: Connection,
                                   scheme: Type[SignatureScheme],
                                   keypair: KeyPair,
                                   identity: BrokerIdentifier
                                   ) -> BrokerIdentifier:
    """Inbound side: verify the dialer first, then authenticate ourselves."""
    peer = await _recv_auth(connection, scheme, keypair)
    await _send_auth(connection, scheme, keypair, identity)
    return peer
