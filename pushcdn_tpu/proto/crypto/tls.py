"""TLS certificate plumbing: a pinned local CA + per-process leaf certs.

Capability parity with cdn-proto/src/crypto/tls.rs:22-155 + build.rs:22-59:
the reference generates a local CA at *build* time and bakes it in; every
process derives a leaf cert (SAN ``espresso``) from that CA at startup, and
clients trust either the baked-in local CA or a hardcoded production CA.

TPU-native redesign: no build step — the local CA is generated once per
machine under a cache dir (or ephemerally in-memory for tests) using the
``cryptography`` package, and leaf certs are derived at process start. The
SAN is ``pushcdn``; clients connecting with ``use_local_authority=True``
trust the local CA and expect that SAN, mirroring the reference's scheme.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
import tempfile
from dataclasses import dataclass
from typing import Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

# The SAN every broker/marshal leaf cert carries (reference uses "espresso",
# tls.rs:52-93).
LOCAL_SAN = "pushcdn"

_CA_CACHE: Optional[Tuple[bytes, bytes]] = None  # (cert_pem, key_pem)


@dataclass
class Certificate:
    """A leaf certificate + key, PEM-encoded, ready for an SSL context."""

    cert_pem: bytes
    key_pem: bytes
    ca_cert_pem: bytes

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        with tempfile.TemporaryDirectory() as d:
            cert_f, key_f = os.path.join(d, "c.pem"), os.path.join(d, "k.pem")
            with open(cert_f, "wb") as f:
                f.write(self.cert_pem)
            with open(key_f, "wb") as f:
                f.write(self.key_pem)
            ctx.load_cert_chain(cert_f, key_f)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Context trusting this cert's CA, expecting SAN ``pushcdn``."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cadata=self.ca_cert_pem.decode())
        ctx.check_hostname = True
        return ctx


def _generate_ca() -> Tuple[bytes, bytes]:
    """Make a fresh CA (parity: scripts/gen-ca.bash + build.rs:22-59)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, "pushcdn local CA"),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "pushcdn-tpu"),
    ])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=3650))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
    )


def load_ca(ca_cert_path: Optional[str] = None,
            ca_key_path: Optional[str] = None) -> Tuple[bytes, bytes]:
    """Load a CA from disk, or fall back to the process-local generated CA
    (parity ``load_ca``, tls.rs:52-70: None → baked-in local CA)."""
    global _CA_CACHE
    if bool(ca_cert_path) != bool(ca_key_path):
        from pushcdn_tpu.proto.error import ErrorKind, bail
        bail(ErrorKind.PARSE,
             "provide both ca_cert_path and ca_key_path, or neither")
    if ca_cert_path and ca_key_path:
        with open(ca_cert_path, "rb") as f:
            cert_pem = f.read()
        with open(ca_key_path, "rb") as f:
            key_pem = f.read()
        return cert_pem, key_pem
    if _CA_CACHE is None:
        _CA_CACHE = _generate_ca()
    return _CA_CACHE


def generate_cert_from_ca(ca_cert_pem: bytes, ca_key_pem: bytes) -> Certificate:
    """Derive a per-process leaf cert with SAN ``pushcdn`` (parity
    ``generate_cert_from_ca``, tls.rs:52-93)."""
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, LOCAL_SAN)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectAlternativeName([
                x509.DNSName(LOCAL_SAN),
                x509.DNSName("localhost"),
                x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
            ]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    return Certificate(
        cert_pem=cert.public_bytes(serialization.Encoding.PEM),
        key_pem=key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ),
        ca_cert_pem=ca_cert_pem,
    )


def local_certificate() -> Certificate:
    """One-call helper: local CA → leaf cert (what binaries use by default)."""
    ca_cert, ca_key = load_ca()
    return generate_cert_from_ca(ca_cert, ca_key)
