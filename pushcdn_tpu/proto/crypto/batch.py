"""Adaptive batching signature verifier for connection storms.

A marshal under a storm verifies many signatures in the same few
milliseconds; pairing schemes amortize dramatically when those checks
share one final exponentiation (``BlsBn254Scheme.verify_batch``, ~1.7 ms
single vs ~0.5 ms/sig at n=8 with warm per-pk line tables — the batched
path fuses every item's cached Miller table onto ONE squaring chain). Batching here is ADAPTIVE —
no coalescing timer: the first arrival verifies immediately (an isolated
auth pays zero extra latency), and anything arriving while a
verification is in flight queues and runs as the next batch. Under a
storm the crypto itself is the window.

Semantics are identical to per-item verification:

- batch accepts ⇒ every item is individually valid (random-linear-
  combination soundness, failure probability 2^-128 per forged item);
- batch rejects ⇒ at least one item is invalid ⇒ items are re-checked
  individually — in parallel threads on multi-core hosts, so a single
  forged signature costs the honest co-batched users ~one extra verify
  of latency; on a single hardware thread the re-check is necessarily
  sequential (parallelism cannot exist there) but still yields to the
  loop between pairings, and can never deny honest users service.

Schemes without ``verify_batch`` (Ed25519 — already microseconds) pass
straight through. On multi-core hosts all crypto runs off the event loop
(ctypes releases the GIL), so a storm's pairings never stall the accept
loop. On a single hardware thread an offload buys no parallelism and
costs two context switches per auth (~0.3-0.7 ms measured), so there the
verifier runs pairings inline and yields to the loop around each one —
co-arrivals still coalesce into batches between the yields.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import List, Optional, Set, Tuple

logger = logging.getLogger("pushcdn.crypto.batch")


class BatchVerifier:
    def __init__(self, scheme, max_batch: int = 32,
                 offload: Optional[bool] = None):
        self.scheme = scheme
        self.max_batch = max_batch
        if offload is None:
            # PUSHCDN_CRYPTO_OFFLOAD=0/1 overrides the autodetect: the
            # affinity is sampled once here, so a later cgroup/affinity
            # change is invisible — an operator who knows better can pin
            # the policy instead of restarting into the right mask
            env = os.environ.get("PUSHCDN_CRYPTO_OFFLOAD", "").strip().lower()
            if env in ("0", "1", "false", "true", "no", "yes", "off", "on"):
                offload = env in ("1", "true", "yes", "on")
                logger.info("crypto offload policy: %s (PUSHCDN_CRYPTO_OFFLOAD)",
                            "thread" if offload else "inline")
            else:
                if env:
                    logger.warning(
                        "PUSHCDN_CRYPTO_OFFLOAD=%r not recognized "
                        "(want 0/1); falling back to autodetect", env)
                # usable CPUs, not machine CPUs: a marshal pinned to one
                # core by taskset/cgroups should take the inline path too
                try:
                    usable = len(os.sched_getaffinity(0))
                except (AttributeError, OSError):
                    usable = os.cpu_count() or 1
                offload = usable > 1
                logger.info(
                    "crypto offload policy: %s (auto: %d usable CPU%s)",
                    "thread" if offload else "inline",
                    usable, "" if usable == 1 else "s")
        self._offload = offload
        self._batchable = hasattr(scheme, "verify_batch")
        self._inflight = False
        self._pending: List[Tuple[tuple, asyncio.Future]] = []
        # strong refs: the loop holds only weak refs to tasks, and a
        # GC'd batch task would leave _inflight wedged True forever
        self._tasks: Set[asyncio.Task] = set()
        # observability (tested, and handy when sizing a deployment)
        self.batches = 0
        self.batched_items = 0
        self.singles = 0

    def cache_stats(self):
        """The scheme's verification-cache counters (the BLS per-public-
        key line-table LRU: repeat connectors replay a cached Miller
        table in both the single and the batched path), or None for
        schemes without one. Complements batches/batched_items when
        sizing a marshal: a high hit rate means even the single-arrival
        path runs at the warm-verify cost."""
        from pushcdn_tpu.proto.crypto.signature import BlsBn254Scheme
        if self.scheme is not BlsBn254Scheme:
            return None
        from pushcdn_tpu.native import bls
        return bls.pk_cache_stats()

    async def verify(self, public_key: bytes, namespace, message: bytes,
                     signature: bytes) -> bool:
        if not self._batchable:
            # microsecond schemes (Ed25519): a thread handoff would cost
            # 10x the verify itself — run inline
            self.singles += 1
            return self.scheme.verify(public_key, namespace, message,
                                      signature)
        item = (public_key, namespace, message, signature)
        if self._inflight:
            fut = asyncio.get_running_loop().create_future()
            self._pending.append((item, fut))
            return await fut
        # idle: verify NOW (no window to wait out); arrivals during this
        # call accumulate into the next batch
        self._inflight = True
        try:
            self.singles += 1
            return await self._call(self.scheme.verify, *item)
        finally:
            self._drain()

    async def _call(self, fn, *args):
        """Run one crypto call per the offload policy, keeping the
        batch-formation window alive either way."""
        if self._offload:
            return await asyncio.to_thread(fn, *args)
        result = fn(*args)
        # the loop was blocked for the call's duration: co-arrivals are
        # queued behind it. Two passes let their handler chains (reader
        # wakeup, then the handler itself) reach verify() and register in
        # _pending before _drain decides whether a batch formed.
        await asyncio.sleep(0)
        await asyncio.sleep(0)
        return result

    def _drain(self) -> None:
        """Kick the queued batch (keeps ``_inflight`` until the queue is
        empty, so a sustained storm stays in batch mode)."""
        batch, self._pending = (self._pending[:self.max_batch],
                                self._pending[self.max_batch:])
        if batch:
            task = asyncio.ensure_future(self._run(batch))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        else:
            self._inflight = False

    async def _run(self, batch) -> None:
        items = [item for item, _ in batch]
        try:
            try:
                if len(items) == 1:
                    self.singles += 1
                    results = [await self._call(self.scheme.verify,
                                                *items[0])]
                else:
                    self.batches += 1
                    self.batched_items += len(items)
                    ok = await self._call(self.scheme.verify_batch, items)
                    if ok:
                        results = [True] * len(items)
                    elif self._offload:
                        # at least one forgery: identify it in PARALLEL so
                        # it cannot serialize the honest co-batched users
                        results = await asyncio.gather(*(
                            asyncio.to_thread(self.scheme.verify, *it)
                            for it in items))
                    else:
                        # single core: parallelism cannot help; re-check
                        # sequentially with a yield per item so the loop
                        # breathes between pairings
                        results = []
                        for it in items:
                            results.append(self.scheme.verify(*it))
                            await asyncio.sleep(0)
                for (_, fut), ok in zip(batch, results):
                    if not fut.done():
                        fut.set_result(ok)
            except BaseException as exc:
                # includes CancelledError: waiters must never hang on a
                # dead batch, and the drain below must still run
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            exc if isinstance(exc, Exception)
                            else ConnectionError("batch verify cancelled"))
                raise
        finally:
            self._drain()