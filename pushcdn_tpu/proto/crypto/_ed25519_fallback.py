"""Pure-Python Ed25519 (RFC 8032) — dependency-gate fallback.

Used by :mod:`signature` only when the ``cryptography`` package (the
OpenSSL-backed default) is not installed in the image. Wire-compatible
with it: raw 32-byte keys, 64-byte signatures, identical deterministic
keygen from the same 32 seed bytes, so a fallback-signed handshake
verifies on a peer running the native backend and vice versa.

Implementation notes: extended homogeneous coordinates with the complete
twisted-Edwards addition law (RFC 8032 §5.1.4) — one unified formula for
add and double, no per-step inversions; a precomputed 2^i·B ladder makes
fixed-base multiplication (keygen/sign) ~2x a generic one. Verification
is the cofactorless strict check (s < L, canonical point encodings),
matching the OpenSSL behavior the rest of the stack assumes. Speed is
~1-3 ms per operation in CPython — three orders slower than OpenSSL but
well inside the auth path's 5 s timeout envelope; images that ship
``cryptography`` never import this module.

SECURITY TRADEOFF — not constant-time. Signing walks the secret scalar
with data-dependent branches and CPython bigint arithmetic, so execution
time correlates with private-key bits; a network attacker who can
trigger many handshakes and measure latency gains a classic timing side
channel that the OpenSSL backend does not have. This is an accepted
limitation of the dependency-gate fallback: it exists so dev/CI images
without the ``cryptography`` wheel can run the full stack. Production
deployments terminating auth for keys that matter must ship
``cryptography`` (or select the native BLS scheme) — do not serve
high-value Ed25519 keys through this module.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
_SQRT_M1 = pow(2, (P - 1) // 4, P)

_IDENT = (0, 1, 1, 0)  # neutral element in extended coordinates


def _point_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    d = 2 * z1 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _scalar_mult(s: int, p):
    q = _IDENT
    while s:
        if s & 1:
            q = _point_add(q, p)
        p = _point_add(p, p)
        s >>= 1
    return q


def _recover_x(y: int, sign: int):
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * _SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_BY = 4 * pow(5, P - 2, P) % P
_BX = _recover_x(_BY, 0)
_B = (_BX, _BY, 1, _BX * _BY % P)

# fixed-base ladder: 2^i * B for i in [0, 256) — covers clamped scalars
# (bit 254 set) and any value reduced mod L
_B_LADDER = []
_tmp = _B
for _ in range(256):
    _B_LADDER.append(_tmp)
    _tmp = _point_add(_tmp, _tmp)
del _tmp


def _scalar_mult_base(s: int):
    q = _IDENT
    i = 0
    while s:
        if s & 1:
            q = _point_add(q, _B_LADDER[i])
        s >>= 1
        i += 1
    return q


def _compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(enc: bytes):
    if len(enc) != 32:
        return None
    val = int.from_bytes(enc, "little")
    sign, y = val >> 255, val & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    return (a & ((1 << 254) - 8)) | (1 << 254)


def _h(*parts: bytes) -> int:
    return int.from_bytes(hashlib.sha512(b"".join(parts)).digest(), "little")


def publickey(private_key: bytes) -> bytes:
    """Raw 32-byte public key for a raw 32-byte private key."""
    h = hashlib.sha512(private_key).digest()
    return _compress(_scalar_mult_base(_clamp(h[:32])))


def sign(private_key: bytes, message: bytes) -> bytes:
    h = hashlib.sha512(private_key).digest()
    a, prefix = _clamp(h[:32]), h[32:]
    pk = _compress(_scalar_mult_base(a))
    r = _h(prefix, message) % L
    r_enc = _compress(_scalar_mult_base(r))
    s = (r + _h(r_enc, pk, message) % L * a) % L
    return r_enc + int.to_bytes(s, 32, "little")


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    if len(signature) != 64 or len(public_key) != 32:
        return False
    a_pt = _decompress(public_key)
    r_pt = _decompress(signature[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False  # malleability rejection, parity with OpenSSL
    k = _h(signature[:32], public_key, message) % L
    lhs = _scalar_mult_base(s)
    rhs = _point_add(r_pt, _scalar_mult(k, a_pt))
    return _compress(lhs) == _compress(rhs)
