"""Signature schemes: namespaced sign/verify behind a pluggable seam.

Capability parity with cdn-proto/src/crypto/signature.rs:19-175:

- ``Namespace`` domain separation (``UserMarshalAuth`` / ``BrokerBrokerAuth``,
  signature.rs:19-32) — a signature over a timestamp for the marshal must
  not be replayable to a broker;
- ``SignatureScheme`` trait (sign/verify over namespaced messages);
- ``KeyPair`` with seeded deterministic generation (parity
  ``DeterministicRng``, crypto/rng.rs:15-42 — reproducible keys for tests);
- Two schemes behind the seam:
  - ``Ed25519Scheme`` — the default (native-speed via the ``cryptography``
    package's OpenSSL backend); small keys, microsecond verify.
  - ``BlsBn254Scheme`` — capability parity with the reference's BLS over
    BN254 from jellyfish (signature.rs:113-175), implemented from scratch
    in C++ (native/bls_bn254.cpp: Montgomery Fp, the Fp2/Fp6/Fp12 tower,
    optimal-ate pairing, try-and-increment hash-to-G1) behind a ctypes
    ABI. Gated on the native library compiling; verification includes the
    G2 subgroup check.
"""

from __future__ import annotations

import abc
import enum
import hashlib
from dataclasses import dataclass

try:  # the OpenSSL backend; images without it use the pure-Python fallback
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # gated dependency: never installed at import time
    _HAVE_CRYPTOGRAPHY = False
    from pushcdn_tpu.proto.crypto import _ed25519_fallback

from pushcdn_tpu.proto.error import ErrorKind, bail


class Namespace(enum.Enum):
    """Signing domains (parity signature.rs:19-32)."""

    USER_MARSHAL_AUTH = b"user-marshal-auth"
    BROKER_BROKER_AUTH = b"broker-broker-auth"


def _namespaced(namespace: Namespace, message: bytes) -> bytes:
    # length-prefix the namespace so (ns, msg) pairs can't collide
    ns = namespace.value
    return len(ns).to_bytes(2, "little") + ns + bytes(message)


@dataclass(frozen=True)
class KeyPair:
    """A serialized (public, private) pair for one scheme."""

    public_key: bytes
    private_key: bytes


class SignatureScheme(abc.ABC):
    """The pluggable scheme seam (parity ``SignatureScheme`` trait,
    signature.rs:36-63). All keys/signatures are opaque bytes at this
    boundary (parity ``Serializable``, signature.rs:66-78)."""

    name: str = "?"

    @classmethod
    @abc.abstractmethod
    def generate_keypair(cls, seed: int | None = None) -> KeyPair:
        """Generate a keypair; a ``seed`` gives deterministic keys for
        reproducible tests (DeterministicRng parity)."""

    @classmethod
    @abc.abstractmethod
    def sign(cls, private_key: bytes, namespace: Namespace,
             message: bytes) -> bytes: ...

    @classmethod
    @abc.abstractmethod
    def verify(cls, public_key: bytes, namespace: Namespace,
               message: bytes, signature: bytes) -> bool: ...


class Ed25519Scheme(SignatureScheme):
    """Default scheme: Ed25519 (32-byte keys, 64-byte signatures)."""

    name = "ed25519"

    @classmethod
    def generate_keypair(cls, seed: int | None = None) -> KeyPair:
        if seed is None:
            import os as _os
            raw = _os.urandom(32)
        else:
            # 32 deterministic bytes from the seed (DeterministicRng parity)
            raw = hashlib.blake2b(seed.to_bytes(8, "little", signed=False),
                                  digest_size=32).digest()
        if not _HAVE_CRYPTOGRAPHY:
            return KeyPair(public_key=_ed25519_fallback.publickey(raw),
                           private_key=raw)
        priv = Ed25519PrivateKey.from_private_bytes(raw)
        from cryptography.hazmat.primitives import serialization
        return KeyPair(
            public_key=priv.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw),
            private_key=priv.private_bytes(
                serialization.Encoding.Raw, serialization.PrivateFormat.Raw,
                serialization.NoEncryption()),
        )

    @classmethod
    def sign(cls, private_key: bytes, namespace: Namespace,
             message: bytes) -> bytes:
        try:
            if not _HAVE_CRYPTOGRAPHY:
                return _ed25519_fallback.sign(bytes(private_key),
                                              _namespaced(namespace, message))
            priv = Ed25519PrivateKey.from_private_bytes(private_key)
            return priv.sign(_namespaced(namespace, message))
        except Exception as exc:
            bail(ErrorKind.CRYPTO, "signing failed", exc)

    @classmethod
    def verify(cls, public_key: bytes, namespace: Namespace,
               message: bytes, signature: bytes) -> bool:
        if not _HAVE_CRYPTOGRAPHY:
            try:
                return _ed25519_fallback.verify(
                    bytes(public_key), _namespaced(namespace, message),
                    bytes(signature))
            except Exception:
                return False
        try:
            pub = Ed25519PublicKey.from_public_bytes(public_key)
            pub.verify(bytes(signature), _namespaced(namespace, message))
            return True
        except (InvalidSignature, ValueError, TypeError):
            return False


class BlsBn254Scheme(SignatureScheme):
    """BLS over BN254 (alt_bn128), min-sig: 128-byte G2 public keys,
    64-byte G1 signatures — the reference's production scheme shape
    (signature.rs:113-175). Backed by the native C++ pairing library;
    check :func:`available` (or ``pushcdn_tpu.native.bls.available``)
    before selecting it in a run config."""

    name = "bls-bn254"

    @staticmethod
    def available() -> bool:
        from pushcdn_tpu.native import bls
        return bls.available()

    @classmethod
    def generate_keypair(cls, seed: int | None = None) -> KeyPair:
        from pushcdn_tpu.native import bls
        if seed is None:
            import os as _os
            raw = _os.urandom(32)
        else:
            raw = hashlib.blake2b(seed.to_bytes(8, "little", signed=False),
                                  digest_size=32).digest()
        try:
            sk, pk = bls.keygen(raw)
        except (AssertionError, ValueError) as exc:
            bail(ErrorKind.CRYPTO, "BLS keygen failed", exc)
        return KeyPair(public_key=pk, private_key=sk)

    @classmethod
    def sign(cls, private_key: bytes, namespace: Namespace,
             message: bytes) -> bytes:
        from pushcdn_tpu.native import bls
        try:
            return bls.sign(private_key, _namespaced(namespace, message))
        except (AssertionError, ValueError) as exc:
            bail(ErrorKind.CRYPTO, "signing failed", exc)

    @classmethod
    def verify(cls, public_key: bytes, namespace: Namespace,
               message: bytes, signature: bytes) -> bool:
        """Verification rides the native per-public-key Miller line-table
        cache (``bls.verify_cached``): a repeat connector — the marshal's
        reconnect-storm steady state — skips the pk-side pairing ladder
        and subgroup check after its first verification. Semantics are
        identical to the uncached path for every input (asserted by the
        in-library self-test, including across LRU eviction); set
        ``PUSHCDN_BLS_PK_CACHE=0`` to disable."""
        import time as _time

        from pushcdn_tpu.native import bls
        from pushcdn_tpu.proto import metrics as metrics_mod
        t0 = _time.perf_counter()
        try:
            return bls.verify_cached(bytes(public_key),
                                     _namespaced(namespace, message),
                                     bytes(signature))
        except (AssertionError, TypeError):
            return False
        finally:
            # handshake-level native-seam accounting: attributes auth CPU
            # on /metrics (cdn_native_seconds{kernel="bls_verify"})
            metrics_mod.NATIVE_BLS_SECONDS.inc(_time.perf_counter() - t0)

    @classmethod
    def verify_batch(cls, items) -> bool:
        """Batch-verify ``[(public_key, namespace, message, signature),
        ...]`` with one shared pairing final-exponentiation (random
        linear combination — the connection-storm path). Semantics match
        verifying each item individually: True iff ALL verify. Per-item
        pk-side Miller loops replay cached line tables fused on one
        shared squaring chain (``bls.verify_batch_cached``)."""
        import os as _os
        import time as _time

        from pushcdn_tpu.native import bls
        from pushcdn_tpu.proto import metrics as metrics_mod
        t0 = _time.perf_counter()
        try:
            return bls.verify_batch(
                [(bytes(pk), _namespaced(ns, msg), bytes(sig))
                 for pk, ns, msg, sig in items],
                _os.urandom(32))
        except (AssertionError, TypeError, ValueError):
            return False
        finally:
            metrics_mod.NATIVE_BLS_SECONDS.inc(_time.perf_counter() - t0)


DEFAULT_SCHEME = Ed25519Scheme
