"""Crypto & identity: signature schemes, TLS cert plumbing, deterministic RNG.

Capability parity with cdn-proto/src/crypto/ (SURVEY.md §1 L3).
"""
