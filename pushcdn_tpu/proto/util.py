"""Small utilities: stable 64-bit hash, human-readable mnemonics, task guards.

Capability parity with cdn-proto/src/util.rs:13-40 (``hash``, ``mnemonic``,
``AbortOnDropHandle``).
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Union

_ADJECTIVES = (
    "amber", "brisk", "calm", "dapper", "eager", "fuzzy", "gentle", "humble",
    "ivory", "jolly", "keen", "lively", "mellow", "noble", "opal", "plucky",
    "quiet", "rustic", "spry", "tidy", "umber", "vivid", "witty", "xenial",
    "young", "zesty", "bold", "crisp", "deft", "earnest", "frank", "glad",
)

_NOUNS = (
    "aspen", "brook", "cedar", "dune", "ember", "fjord", "glade", "harbor",
    "inlet", "juniper", "knoll", "lagoon", "meadow", "nimbus", "orchard",
    "prairie", "quartz", "ridge", "summit", "thicket", "upland", "vale",
    "willow", "yonder", "zephyr", "basin", "cliff", "delta", "eddy", "falls",
    "grove", "heath",
)


def stable_hash64(data: Union[bytes, bytearray, memoryview, str]) -> int:
    """Deterministic 64-bit hash of ``data`` (stable across processes).

    Python's builtin ``hash`` is salted per-process, so we use blake2b.
    Parity: cdn-proto/src/util.rs `hash` (a 64-bit content hash used for
    mnemonic ids and routing-table keys).
    """
    if isinstance(data, str):
        data = data.encode("utf-8")
    return int.from_bytes(hashlib.blake2b(bytes(data), digest_size=8).digest(), "little")


def mnemonic(data: Union[bytes, bytearray, memoryview, str, int]) -> str:
    """Human-readable id like ``"brisk-lagoon-1f"`` for logs.

    Parity: cdn-proto/src/util.rs `mnemonic` — the reference logs connect /
    disconnect events with mnemonic'd public keys.
    """
    h = data if isinstance(data, int) else stable_hash64(data)
    adj = _ADJECTIVES[h & 31]
    noun = _NOUNS[(h >> 5) & 31]
    tail = (h >> 10) & 0xFF
    return f"{adj}-{noun}-{tail:02x}"


class AbortOnDropHandle:
    """Holds an asyncio task and cancels it on :meth:`abort` or GC.

    Parity: cdn-proto/src/util.rs `AbortOnDropHandle` — per-connection
    receive loops are aborted when their owning connection is removed.
    """

    def __init__(self, task: asyncio.Task):
        self._task = task

    def abort(self) -> None:
        if not self._task.done():
            self._task.cancel()

    @property
    def task(self) -> asyncio.Task:
        return self._task

    def __del__(self) -> None:  # best-effort; explicit abort() is the norm
        try:
            self.abort()
        except Exception:
            pass
