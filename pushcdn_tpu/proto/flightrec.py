"""Per-connection / per-task flight recorder: the last N structured events
before something died.

The transports and broker maps already log *that* a connection failed; what
the operator cannot see is what happened in the seconds BEFORE — was the
peer backpressured, mid-auth, waiting on a pool permit, replaying a sync?
Every :class:`pushcdn_tpu.proto.transport.base.Connection` (and the
supervised background tasks) carries a :class:`FlightRecorder`: a
fixed-size ``collections.deque`` ring of ``(t_monotonic, event, detail)``
tuples — appends never allocate beyond the tuple itself and old events
fall off the far end, so the hot path pays one deque append per *event*
(connect/auth/subscribe/sync/backpressure/limiter-wait/error), never per
frame.

Dump policy: events marked ``abnormal`` arm the recorder; an armed
recorder's trail is written to the diagnostics log (``pushcdn.flightrec``)
when the owner tears the connection down (``maybe_dump``). A clean close
never logs. All live recorders are also readable on demand via
``GET /debug/flightrec`` on the metrics endpoint
(:func:`pushcdn_tpu.proto.metrics.serve_metrics`).
"""

from __future__ import annotations

import collections
import logging
import time
import weakref
from typing import Optional

logger = logging.getLogger("pushcdn.flightrec")

DEFAULT_EVENTS = 64

# every live recorder, for the /debug/flightrec dump; weak so an abandoned
# connection's recorder disappears with it
_LIVE: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


class FlightRecorder:
    """Fixed-size ring of structured events attached to one connection or
    task. Not thread-safe by design: the owner's event loop is the only
    writer (deque appends are atomic enough for the /debug reader)."""

    __slots__ = ("label", "abnormal", "_dumped", "_events", "__weakref__")

    def __init__(self, label: str, capacity: int = DEFAULT_EVENTS):
        self.label = label
        self.abnormal = False
        self._dumped = False
        self._events: collections.deque = collections.deque(maxlen=capacity)
        _LIVE.add(self)

    def record(self, event: str, detail="", abnormal: bool = False) -> None:
        """Append one event. ``detail`` is kept as-is (formatted only at
        dump time). ``abnormal=True`` arms the recorder: the next
        :meth:`maybe_dump` writes the whole trail to the log."""
        if abnormal:
            self.abnormal = True
        self._events.append((time.monotonic(), event, detail))

    def __len__(self) -> int:
        return len(self._events)

    def trail(self, max_events: Optional[int] = None) -> str:
        """The formatted trail: one line per event, age-relative.
        ``max_events`` keeps only the most recent N (the interesting end
        of the ring)."""
        now = time.monotonic()
        events = list(self._events)
        dropped = 0
        if max_events is not None and len(events) > max_events:
            dropped = len(events) - max_events
            events = events[-max_events:] if max_events > 0 else []
        lines = [f"flight recorder [{self.label}] "
                 f"({len(self._events)} events"
                 + (f", showing last {len(events)}" if dropped else "")
                 + ")"]
        for t, event, detail in events:
            if isinstance(detail, str):
                d = f"  {detail}" if detail else ""
            else:
                d = f"  {detail!r}"
            lines.append(f"  -{now - t:9.3f}s  {event}{d}")
        return "\n".join(lines)

    def dump(self, reason: str = "") -> None:
        """Unconditionally write the trail to the diagnostics log."""
        self._dumped = True
        logger.warning("abnormal disconnect%s:\n%s",
                       f" ({reason})" if reason else "", self.trail())

    def maybe_dump(self, reason: str = "") -> bool:
        """Dump the trail iff an abnormal event armed the recorder —
        AT MOST ONCE per recorder: a failed send poisons the connection
        (which dumps) and then removes the peer (which would dump the
        near-identical trail again). Disarms either way. Returns whether
        a dump happened."""
        if not self.abnormal:
            return False
        self.abnormal = False
        if self._dumped:
            return False
        self.dump(reason)
        return True


DEFAULT_RENDER_LIMIT = 10_000


def render_all(limit: Optional[int] = None) -> str:
    """Every live recorder's trail — the ``/debug/flightrec`` body.

    ``limit`` caps the TOTAL number of events rendered (default 10000,
    overridable via the endpoint's ``?limit=`` query): a broker holding
    tens of thousands of connections must not build an unbounded response
    body inside its event loop."""
    if limit is None:
        limit = DEFAULT_RENDER_LIMIT
    recs = sorted(_LIVE, key=lambda r: r.label)
    if not recs:
        return "0 flight recorders\n"
    out = [f"{len(recs)} flight recorders (event limit {limit})", ""]
    budget = max(limit, 0)
    shown = 0
    for r in recs:
        if budget <= 0:
            out.append(f"... truncated: {len(recs) - shown} more "
                       f"recorders past the {limit}-event limit "
                       "(raise ?limit=)")
            break
        out.append(r.trail(max_events=budget))
        budget -= min(len(r), budget)
        shown += 1
    return "\n".join(out) + "\n"


_task_recorder: Optional[FlightRecorder] = None


def task_recorder() -> FlightRecorder:
    """The per-process recorder shared by supervised background tasks
    (restarts and deaths are rare, global events — they don't need a ring
    per task)."""
    global _task_recorder
    if _task_recorder is None:
        _task_recorder = FlightRecorder("supervised-tasks", capacity=128)
    return _task_recorder
