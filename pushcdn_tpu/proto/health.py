"""Liveness / readiness plane: ``GET /healthz`` and ``GET /readyz``.

Push-CDN's premise is a *centrally tracked* topology — operators (and the
load balancer in front of a marshal fleet) need a machine-readable answer
to "is this process healthy" and "should it receive traffic" that is
cheaper and stricter than scraping /metrics and eyeballing gauges.

Two registries of named checks:

- **liveness** (``/healthz``): "is the event loop making progress" — the
  built-in checks cover loop lag (the supervised sampler's most recent
  wakeup ran on time) and the supervised background samplers being alive.
  A failing liveness check means restart-me; the HTTP 200/503 split is
  what a container runtime probes.
- **readiness** (``/readyz``): "can this process do its job right now" —
  components register their own checks (broker: listeners bound, discovery
  reachable, mesh formed-or-intentionally-solo; marshal: listener +
  discovery; client binary: broker link up). Readiness additionally gates
  on the process-wide **drain latch**: :func:`set_draining` flips /readyz
  to 503 *before* listeners close, so a load balancer stops routing to a
  broker while its in-flight traffic still drains.

Every readiness TRANSITION is recorded in the process flight recorder
(``ready-flip`` event, with the failing checks' names) so a post-mortem
``/debug/flightrec`` trail shows *why* a process left rotation, not just
that it did.

Checks are callables returning ``bool`` or ``(bool, detail)``; they may be
coroutines (the readiness evaluation is awaited by the HTTP handler — the
broker's discovery probe uses this for its cached-TTL active probe). A
check that raises reports unhealthy with the exception text, never takes
the endpoint down.

**This module never initializes jax** (same rule as ``cdn_build_info``):
a /healthz probe against a broker that never touched an accelerator must
not pay a multi-second backend bring-up.
"""

from __future__ import annotations

import inspect
import json
import time
from typing import Callable, Dict, Optional, Tuple

# name -> callable() -> bool | (bool, detail) | awaitable of either
LIVENESS: Dict[str, Callable] = {}
READINESS: Dict[str, Callable] = {}

# process-wide drain latch: a non-None reason forces /readyz to 503
# regardless of the registered checks (set BEFORE listeners close)
_draining: Optional[str] = None

# last readiness verdict (overall bool, sorted failing-check names) — a
# flip of EITHER records a ready-flip event, so a change of *reason*
# while staying not-ready still shows up in the trail
_last_state: Optional[tuple] = None


def register_liveness(name: str, fn: Callable) -> None:
    LIVENESS[name] = fn


def register_readiness(name: str, fn: Callable) -> None:
    READINESS[name] = fn


def unregister(name: str) -> None:
    LIVENESS.pop(name, None)
    READINESS.pop(name, None)


def draining() -> Optional[str]:
    return _draining


def set_draining(reason: str = "shutdown") -> None:
    """Flip readiness to false process-wide (the drain latch). Records the
    ``ready-flip`` flight-recorder event immediately — not at the next
    /readyz scrape — so the trail timestamps the moment the process left
    rotation even if nobody probes it again."""
    global _draining, _last_state
    if _draining is not None:
        return
    _draining = reason
    state = (False, ("draining",))
    if _last_state != state:
        _last_state = state
        _record_flip(False, [f"draining: {reason}"], abnormal=False)


def clear_draining() -> None:
    """Re-enter rotation (tests; aborted shutdowns)."""
    global _draining
    _draining = None


def _record_flip(ready: bool, failing, abnormal: bool) -> None:
    from pushcdn_tpu.proto import flightrec
    detail = "ready" if ready else f"NOT ready ({'; '.join(failing)})"
    flightrec.task_recorder().record("ready-flip", detail, abnormal=abnormal)


async def _run_checks(checks: Dict[str, Callable]) -> Dict[str, Tuple[bool, str]]:
    out: Dict[str, Tuple[bool, str]] = {}
    for name, fn in list(checks.items()):
        try:
            res = fn()
            if inspect.isawaitable(res):
                res = await res
        except Exception as exc:  # a broken check reports, never crashes
            res = (False, f"check raised: {exc!r}")
        if isinstance(res, tuple):
            ok, detail = bool(res[0]), str(res[1])
        else:
            ok, detail = bool(res), ""
        out[name] = (ok, detail)
    return out


def _body(ok: bool, checks: Dict[str, Tuple[bool, str]],
          extra: Optional[dict] = None) -> str:
    doc = {
        "status": "ok" if ok else "unhealthy",
        "checks": {name: {"ok": c_ok, "detail": detail}
                   for name, (c_ok, detail) in sorted(checks.items())},
        "ts": time.time(),
    }
    if extra:
        doc.update(extra)
    return json.dumps(doc, separators=(",", ":")) + "\n"


async def render_healthz() -> Tuple[int, str]:
    """Evaluate liveness: (http_status, json_body)."""
    checks = await _run_checks(LIVENESS)
    ok = all(c_ok for c_ok, _ in checks.values())
    return (200 if ok else 503), _body(ok, checks)


async def render_readyz() -> Tuple[int, str]:
    """Evaluate readiness: (http_status, json_body). Detects transitions
    — of the overall verdict OR of the failing-check set — and records
    them as flight-recorder ``ready-flip`` events."""
    global _last_state
    checks = await _run_checks(READINESS)
    if _draining is not None:
        checks = dict(checks)
        checks["draining"] = (False, _draining)
    failing_names = tuple(sorted(
        name for name, (c_ok, _d) in checks.items() if not c_ok))
    failing = [f"{name}: {checks[name][1]}" if checks[name][1] else name
               for name in failing_names]
    ready = not failing_names
    state = (ready, failing_names)
    if state != _last_state:
        # an unexpected check failure is abnormal (arms the recorder so the
        # trail hits the diagnostics log); an intentional drain is not
        _record_flip(ready, failing,
                     abnormal=not ready and failing_names != ("draining",))
        _last_state = state
    return (200 if ready else 503), _body(
        ready, checks, extra={"draining": _draining is not None})


def reset_for_tests() -> None:
    """Drop all registrations + latches (test isolation)."""
    global _draining, _last_state
    LIVENESS.clear()
    READINESS.clear()
    _draining = None
    _last_state = None
