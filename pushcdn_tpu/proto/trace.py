"""Sampled message-lifecycle tracing: where a message spends its time,
client publish → marshal auth → broker ingress → route plan → egress →
receiver delivery.

A *trace* is ``(trace_id, origin_ns)`` — a u64 id plus the wall-clock
nanosecond timestamp of the segment's origin. It rides the wire inside the
frame's kind byte: hot frames (Direct/Broadcast) and the marshal auth
frame may set the high bit (:data:`TRACE_FLAG`) of the kind tag, followed
by a fixed 16-byte ``<u64 trace_id, u64 origin_ns>`` block inserted right
after the kind byte. Untraced frames are byte-identical to the pre-trace
wire (the flag bit was reserved/always-zero: kind tags are 1-9), so they
pay **zero** bytes and zero decode work — every hot-path dispatch tests
the exact kind value and never sees a flagged frame.

Sampling is deterministic and client-side: every ``PUSHCDN_TRACE_SAMPLE``-th
published message is stamped (default 1024, i.e. 1/1024; ``0`` disables
tracing entirely). The first publish after a (re)connect reuses the
connection's trace id, which the marshal-auth span also carries — so one
cluster run always yields at least one COMPLETE chain
(auth → publish → ingress → plan → egress → delivery) under any sampling
rate.

Span emission is a histogram observe per hop
(``cdn_trace_hop_seconds{hop=...}``, latency measured from the trace
origin) plus an in-process ring (:data:`recent`) and an optional JSONL
log (``PUSHCDN_TRACE_LOG=/path/file.jsonl``) for cross-process chain
assembly. Traced frames cross the broker's cut-through plane on the
*instrumented scalar path*: the native header scan stops at the flag bit
(route_plan.cpp, same mechanism as the control-frame stop) so the rest of
the chunk keeps the batch path — the overhead of tracing is confined to
the sampled frames by construction.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Optional, Tuple

from pushcdn_tpu.proto import metrics as metrics_mod
# the wire-level flag bit lives with the codec (single source of truth):
# kind-tag high bit = "a 16-byte trace block follows the kind byte". Legal
# on Direct/Broadcast (decoded by proto.message) and the marshal auth
# frame (handled here at the frame level); everything else treats a
# flagged kind as unknown (disconnect), exactly like a pre-trace node.
from pushcdn_tpu.proto.message import (TRACE_BLOCK, TRACE_FLAG,
                                       pack_trace, unpack_trace)

KIND_MASK = 0x7F

TRACE_BLOCK_BYTES = TRACE_BLOCK.size  # 16 (<u64 trace_id, u64 origin_ns>)

# The lifecycle hops, in chain order.
HOPS = ("publish", "auth", "ingress", "plan", "egress", "delivery")

# (trace_id, origin_ns) or (trace_id, origin_ns, view): consensus-shaped
# workloads tag the u32 view number so per-view SLOs are derivable from
# the span log (see proto.message.TRACE_VIEW_FLAG for the wire encoding).
Trace = Tuple[int, ...]


def _env_sample() -> int:
    raw = os.environ.get("PUSHCDN_TRACE_SAMPLE", "").strip()
    if not raw:
        return 1024
    try:
        return max(int(raw), 0)
    except ValueError:
        return 1024


SAMPLE_EVERY = _env_sample()
ENABLED = SAMPLE_EVERY > 0

HOP_LATENCY = metrics_mod.TRACE_HOP_LATENCY
_HOP_CHILDREN = {hop: HOP_LATENCY.labels(hop=hop) for hop in HOPS}

# Last spans emitted IN THIS PROCESS: (hop, trace_id, origin_ns, t_ns,
# detail). Tests and debug tooling read this; cross-process chains use the
# JSONL log.
recent: collections.deque = collections.deque(maxlen=512)

_LOG_PATH = os.environ.get("PUSHCDN_TRACE_LOG") or None
_log_file = None


def set_log_path(path: Optional[str]) -> Optional[str]:
    """Redirect (or disable, with ``None``) the JSONL span log at runtime;
    returns the previous path. ``PUSHCDN_TRACE_LOG`` seeds the initial
    value at import; in-process drivers (testing.consensus) use this to
    capture spans without re-importing."""
    global _LOG_PATH, _log_file
    prev = _LOG_PATH
    if _log_file is not None:
        try:
            _log_file.close()
        except Exception:
            pass
        _log_file = None
    _LOG_PATH = path
    return prev


def _log(record: dict) -> None:
    global _log_file, _LOG_PATH
    if _log_file is None:
        try:
            _log_file = open(_LOG_PATH, "a", buffering=1)
        except OSError:
            _LOG_PATH = None  # never retry a broken path per span
            return
    try:
        _log_file.write(json.dumps(record, separators=(",", ":")) + "\n")
    except Exception:
        pass


def emit(hop: str, trace: Trace, detail: str = "") -> None:
    """Record one span: per-hop latency histogram + recent ring (+ JSONL
    when ``PUSHCDN_TRACE_LOG`` is set). ``trace`` is the carried
    ``(trace_id, origin_ns)`` or ``(trace_id, origin_ns, view)``; latency
    is wall-clock now minus origin (cross-process on one machine; clock
    skew applies across machines)."""
    tid, origin = trace[0], trace[1]
    view = trace[2] if len(trace) > 2 else None
    now = time.time_ns()
    lat = (now - origin) / 1e9
    if lat < 0.0:
        lat = 0.0
    child = _HOP_CHILDREN.get(hop)
    (child if child is not None
     else HOP_LATENCY.labels(hop=hop)).observe(lat)
    if hop == "delivery":
        # the SLO histogram: publish→delivery as the receiver saw it, with
        # an OpenMetrics exemplar pinning the bucket to this trace id
        metrics_mod.E2E_LATENCY.observe(
            lat, exemplar={"trace_id": f"{tid:016x}"})
    recent.append((hop, tid, origin, now, detail))
    if _LOG_PATH:
        record = {"hop": hop, "trace_id": tid, "origin_ns": origin,
                  "t_ns": now, "lat_s": round(lat, 9), "detail": detail}
        if view is not None:
            record["view"] = view
        _log(record)


def new_trace(view: Optional[int] = None) -> Trace:
    """A fresh trace context originating NOW, optionally view-tagged."""
    if view is None:
        return (_next_id(), time.time_ns())
    return (_next_id(), time.time_ns(), view)


_id_state = (os.getpid() << 40) ^ (time.time_ns() & 0xFFFFFFFFFF)


def _next_id() -> int:
    # splitmix64 step over a per-process seed: unique-enough u64 ids with
    # no coordination, cheap, and never 0
    global _id_state
    _id_state = (_id_state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = _id_state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    z = z ^ (z >> 31)
    return z or 1


class Sampler:
    """Deterministic 1-in-N publish sampler (one per client). ``pending``
    is the connection trace id: the first sampled decision after a
    (re)connect is forced and reuses that id, chaining the auth span to a
    message lifecycle. ``view``, when set (consensus workloads), tags every
    sampled trace with the current view number."""

    __slots__ = ("every", "_n", "pending", "view")

    def __init__(self, every: int = SAMPLE_EVERY):
        self.every = every
        self._n = 0
        self.pending: Optional[int] = None
        self.view: Optional[int] = None

    def next_trace(self) -> Optional[Trace]:
        if self.every <= 0:
            return None
        if self.pending is not None:
            tid, self.pending = self.pending, None
            if self.view is None:
                return (tid, time.time_ns())
            return (tid, time.time_ns(), self.view)
        self._n += 1
        if self._n % self.every:
            return None
        return new_trace(self.view)


# -- frame-level stamp/strip (for frames whose decoded type carries no
#    trace seat, e.g. the marshal auth handshake) -----------------------


def stamp_frame(frame: bytes, trace: Trace) -> bytes:
    """Set the trace flag on a serialized frame: flagged kind byte + the
    16- or 20-byte (view-tagged) trace block + the original remainder."""
    return bytes((frame[0] | TRACE_FLAG,)) + pack_trace(trace) + frame[1:]


def strip_frame(frame) -> Tuple[bytes, Optional[Trace]]:
    """Inverse of :meth:`stamp_frame`: returns ``(plain_frame, trace)``
    with ``trace=None`` (and the input untouched) for unflagged frames."""
    if len(frame) < 1 + TRACE_BLOCK_BYTES or not frame[0] & TRACE_FLAG:
        return (frame if isinstance(frame, bytes) else bytes(frame)), None
    trace, off = unpack_trace(frame, 1)
    return bytes((frame[0] & KIND_MASK,)) + bytes(frame[off:]), trace
