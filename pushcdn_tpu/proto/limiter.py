"""Flow control: a global byte-denominated memory pool with RAII-style
permits attached to message buffers, plus optional per-connection queue
depth bounds.

Capability parity with the reference's limiter
(cdn-proto/src/connection/limiter/mod.rs:15-75, limiter/pool.rs:28-111):

- The pool is a semaphore denominated in *bytes*. A connection's reader task
  acquires ``len(message)`` permits **before** allocating the receive buffer
  (protocols/mod.rs:328), so many large in-flight messages cannot OOM the
  broker ("block the reader, not the router").
- The permit is attached to the decoded byte buffer (``Bytes``) and released
  only when the **last clone** drops — i.e. after broadcast fan-out to every
  recipient queue has completed (pool.rs:7-14, :85-111).
- Permit-lifetime (allocation → final release) is the reference's latency
  proxy metric (pool.rs:44-52); we record it the same way.

TPU lowering note: on the device data plane the analog of this pool is a
fixed ring of HBM frame slots — credit accounting over slots instead of
bytes (see pushcdn_tpu.parallel.frames.FrameRing).
"""

from __future__ import annotations

import asyncio
import time
import weakref
from typing import Optional

from pushcdn_tpu.proto.error import ErrorKind, bail

# Live pools, for the metrics pre-render occupancy gauge
# (cdn_pool_bytes{state=...}); weak so a dropped Limiter's pool vanishes.
LIVE_POOLS: "weakref.WeakSet[MemoryPool]" = weakref.WeakSet()


class _ByteSemaphore:
    """An asyncio semaphore that acquires/releases in arbitrary byte counts.

    ``asyncio.Semaphore`` only steps by 1; we need `acquire(n_bytes)` with
    FIFO fairness so one huge frame can't be starved by streams of small
    ones (parity with tokio's `Semaphore::acquire_many` used at
    pool.rs:60-68).
    """

    def __init__(self, capacity: int):
        self._capacity = capacity
        self._available = capacity
        self._wait_list: list[tuple[int, asyncio.Future]] = []

    async def acquire(self, n: int) -> None:
        if n <= self._available and not self._wait_list:
            self._available -= n
            return
        fut = asyncio.get_running_loop().create_future()
        self._wait_list.append((n, fut))
        try:
            await fut
        except asyncio.CancelledError:
            if (n, fut) in self._wait_list:
                self._wait_list.remove((n, fut))
            elif fut.done() and not fut.cancelled():
                # Woken and cancelled concurrently: hand the grant back.
                self.release(n)
            raise

    def try_acquire(self, n: int) -> bool:
        """Synchronous fast path: take ``n`` without suspending, or return
        False when the acquisition would have to wait. FIFO fairness is the
        same invariant ``acquire`` keeps — never jump an existing waiter."""
        if self._wait_list or n > self._available:
            return False
        self._available -= n
        return True

    def release(self, n: int) -> None:
        self._available += n
        self._wake()

    def _wake(self) -> None:
        # FIFO: only the head waiter may proceed (prevents small-frame
        # starvation of a large waiter).
        while self._wait_list:
            n, fut = self._wait_list[0]
            if fut.cancelled():
                self._wait_list.pop(0)
                continue
            if n > self._available:
                break
            self._wait_list.pop(0)
            self._available -= n
            fut.set_result(None)

    @property
    def available(self) -> int:
        return self._available


class AllocationPermit:
    """A byte reservation in a :class:`MemoryPool`; release exactly once.

    Python has no deterministic drop, so release is explicit (the last
    ``Bytes`` clone releases it) with a GC backstop. Records the
    allocation-lifetime latency sample on release (parity pool.rs:44-52).
    """

    __slots__ = ("_pool", "nbytes", "_released", "_t_alloc", "__weakref__")

    def __init__(self, pool: "MemoryPool", nbytes: int):
        self._pool = pool
        self.nbytes = nbytes
        self._released = False
        self._t_alloc = time.monotonic()

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._pool._on_release(self.nbytes, time.monotonic() - self._t_alloc)

    def __del__(self):  # GC backstop only
        try:
            self.release()
        except Exception:
            pass


class Bytes:
    """A refcounted message buffer carrying its pool permit.

    Parity: ``Allocation<Vec<u8>>`` aka ``Bytes``
    (cdn-proto/src/connection/mod.rs:19, pool.rs:85-111) — cloned per
    recipient during fan-out with **no copy** of the underlying buffer; the
    permit returns to the pool when the last clone is released.
    """

    __slots__ = ("data", "_permit", "_refs")

    def __init__(self, data, permit: Optional[AllocationPermit] = None):
        self.data = data  # bytes or memoryview
        self._permit = permit
        self._refs = [1]  # shared mutable refcount across clones

    def clone(self) -> "Bytes":
        self._refs[0] += 1
        b = Bytes.__new__(Bytes)
        b.data = self.data
        b._permit = self._permit
        b._refs = self._refs
        return b

    def release(self) -> None:
        self._refs[0] -= 1
        if self._refs[0] == 0 and self._permit is not None:
            self._permit.release()

    def __len__(self) -> int:
        return len(self.data)

    def __bytes__(self) -> bytes:
        return bytes(self.data)


class BytesLease:
    """Batch-wise permit transfer: holds ONE clone reference of a
    :class:`Bytes` until this object is garbage-collected.

    The cut-through routing plane hands whole-chunk byte ranges to
    connection writers as zero-copy views (``PreEncoded.data``); the
    chunk's single pool permit must outlive every pending flush that
    still reads its buffer. A lease rides each writer entry's ``owner``
    seat, so the permit releases when the LAST flush (or queue drain)
    drops its entry — the chunk-granular analog of the per-frame
    ``Bytes.clone()`` fan-out accounting.
    """

    __slots__ = ("_b",)

    def __init__(self, b: "Bytes"):
        self._b = b.clone()

    def __del__(self):
        b, self._b = self._b, None
        if b is not None:
            try:
                b.release()
            except Exception:
                pass


class MemoryPool:
    """Global byte budget for in-flight message buffers.

    Parity: ``MemoryPool`` (pool.rs:28-68). Broker default is 1 GiB
    (cdn-broker/src/binaries/broker.rs:67-72).
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            bail(ErrorKind.PARSE, "memory pool capacity must be positive")
        self.capacity = capacity_bytes
        self._sem = _ByteSemaphore(capacity_bytes)
        # latency proxy: permit alloc→release lifetimes (metrics hook)
        self.latency_samples: list[float] = []
        self._latency_cap = 4096
        # pressure hooks (durable-topic retention, ISSUE 14): callables
        # fn(deficit_bytes) invoked synchronously when an allocation is
        # about to wait — a holder of idle permits (retention leases) can
        # give them back so a blocked reader never deadlocks against
        # passively-held reservations
        self._reclaimers: list = []
        LIVE_POOLS.add(self)

    def add_reclaimer(self, fn) -> None:
        self._reclaimers.append(fn)

    def remove_reclaimer(self, fn) -> None:
        try:
            self._reclaimers.remove(fn)
        except ValueError:
            pass

    def _run_reclaimers(self, deficit: int) -> None:
        for fn in list(self._reclaimers):
            try:
                fn(deficit)
            except Exception:  # a broken hook must not wedge the reader
                pass
            if self._sem.available >= deficit >= 0:
                break

    async def allocate(self, nbytes: int) -> AllocationPermit:
        """Reserve ``nbytes``; blocks (backpressuring the reader) until the
        pool has room. A single message larger than the whole pool is an
        error rather than a deadlock."""
        if nbytes > self.capacity:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"message of {nbytes} B exceeds pool capacity {self.capacity} B")
        if self._sem.try_acquire(nbytes):
            return AllocationPermit(self, nbytes)
        # about to wait: let passive permit holders (retention) release
        # before the reader blocks — "block the reader, not the router"
        # must never become "wedge the reader behind idle leases"
        if self._reclaimers:
            self._run_reclaimers(nbytes)
        await self._sem.acquire(nbytes)
        return AllocationPermit(self, nbytes)

    def try_allocate(self, nbytes: int) -> Optional[AllocationPermit]:
        """Synchronous fast path: reserve ``nbytes`` without suspending, or
        return None when the reservation would have to wait (FIFO fairness
        preserved: never jumps an existing waiter). The reader's batch scan
        uses this so the common non-backpressured case costs no awaits."""
        if nbytes > self.capacity:
            bail(ErrorKind.EXCEEDED_SIZE,
                 f"message of {nbytes} B exceeds pool capacity {self.capacity} B")
        if not self._sem.try_acquire(nbytes):
            return None
        return AllocationPermit(self, nbytes)

    def _on_release(self, nbytes: int, lifetime_s: float) -> None:
        self._sem.release(nbytes)
        if len(self.latency_samples) < self._latency_cap:
            self.latency_samples.append(lifetime_s)

    @property
    def available(self) -> int:
        return self._sem.available


class Limiter:
    """Bundle of the global pool + optional per-connection queue bound.

    Parity: ``Limiter`` (limiter/mod.rs:15-21): global byte pool shared by
    every connection, plus an optional bound on each connection's channel
    depth (applied by the transport when building queues,
    protocols/mod.rs:149-153).
    """

    def __init__(self, global_pool_bytes: Optional[int] = None,
                 per_connection_queue: Optional[int] = None):
        self.pool = MemoryPool(global_pool_bytes) if global_pool_bytes else None
        self.per_connection_queue = per_connection_queue

    async def allocate_message_bytes(self, nbytes: int) -> Optional[AllocationPermit]:
        if self.pool is None:
            return None
        return await self.pool.allocate(nbytes)

    def queue_size(self) -> int:
        # 0 = unbounded for asyncio.Queue
        return self.per_connection_queue or 0


NO_LIMIT = Limiter(None, None)
