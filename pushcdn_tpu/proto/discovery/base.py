"""Discovery interface + broker identity.

Capability parity with cdn-proto/src/discovery/mod.rs:28-129:

- ``DiscoveryClient``: new / perform_heartbeat / get_with_least_connections
  / get_other_brokers / issue_permit / validate_permit / set_whitelist /
  check_whitelist.
- ``BrokerIdentifier`` = {public_advertise_endpoint,
  private_advertise_endpoint}, string-encoded ``"pub/priv"`` and **totally
  ordered** so it can double as the CRDT conflict identity.

TPU-native note (SURVEY.md §2e): on a TPU pod the broker *mesh* topology is
static — ``get_other_brokers`` for device-resident broker shards is answered
from mesh coordinates (pushcdn_tpu.parallel.mesh) rather than a registry;
the registry remains the durable store for permits + whitelist and for
host-level (multi-pod / edge) membership.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional

from pushcdn_tpu.proto.error import ErrorKind, bail


# Permit value space: 0 = failure, 1 = bare ack, real permits are drawn
# from randbits(62) + 2 by every issuer (embedded + redis). The wire
# field is a u64, so validators MUST range-check before touching storage
# — SQLite INTEGER is signed 64-bit and a hostile permit >= 2^63 would
# otherwise surface as OverflowError instead of a clean rejection
# (found by tests/test_fuzz_auth.py).
PERMIT_MIN = 2
PERMIT_MAX = (1 << 62) + 1


def permit_in_range(permit: int) -> bool:
    return PERMIT_MIN <= permit <= PERMIT_MAX



@dataclass(frozen=True, order=True)
class BrokerIdentifier:
    """Identity = the two endpoints a broker advertises.

    ``public_advertise_endpoint`` is for users, ``private_advertise_endpoint``
    for peer brokers. The derived total order (lexicographic over the pair)
    is load-bearing: it is the CRDT conflict tie-breaker AND the pairwise
    dial-dedup rule (only dial peers ≥ self, heartbeat.rs:69-73).
    """

    public_advertise_endpoint: str
    private_advertise_endpoint: str

    def __str__(self) -> str:
        return f"{self.public_advertise_endpoint}/{self.private_advertise_endpoint}"

    @classmethod
    def from_string(cls, s: str) -> "BrokerIdentifier":
        pub, sep, priv = s.partition("/")
        if not sep:
            bail(ErrorKind.PARSE, f"malformed broker identifier {s!r}")
        return cls(pub, priv)


class DiscoveryClient(abc.ABC):
    """The membership/permits/whitelist store interface (discovery/mod.rs:28-76).

    Implementations: :class:`~pushcdn_tpu.proto.discovery.embedded.Embedded`
    (SQLite; local/testing) and
    :class:`~pushcdn_tpu.proto.discovery.redis.Redis` (KeyDB; production,
    gated on a redis client being available).
    """

    @classmethod
    @abc.abstractmethod
    async def new(cls, endpoint: str,
                  identity: Optional[BrokerIdentifier] = None) -> "DiscoveryClient":
        """Connect to the store at ``endpoint``; brokers pass their identity,
        marshals/clients pass None."""

    @abc.abstractmethod
    async def perform_heartbeat(self, num_connections: int,
                                heartbeat_expiry_s: float) -> None:
        """Publish liveness + load; membership ages out after the expiry
        (60 s TTL in the reference, heartbeat.rs:37-50)."""

    async def deregister(self) -> None:
        """Remove this broker's membership row immediately (ISSUE 12 drain):
        a draining broker must leave placement rotation NOW, not after its
        heartbeat TTL ages out. Permits/whitelist are untouched — the row
        would re-appear on the next heartbeat, so drainers also stop
        heartbeating. Default: no-op for identity-less clients."""

    @abc.abstractmethod
    async def get_other_brokers(self) -> List[BrokerIdentifier]:
        """All live brokers except self."""

    @abc.abstractmethod
    async def get_with_least_connections(self) -> BrokerIdentifier:
        """The least-loaded live broker; load = connections + outstanding
        permits (redis.rs:139-167)."""

    @abc.abstractmethod
    async def issue_permit(self, for_broker: BrokerIdentifier,
                           expiry_s: float, public_key: bytes) -> int:
        """Create a single-use permit (>1) bound to ``for_broker`` with a
        TTL (30 s in the reference, auth/marshal.rs:121-135)."""

    async def validate_permit(self, broker: BrokerIdentifier,
                              permit: int) -> Optional[bytes]:
        """Redeem-and-delete (GETDEL semantics): returns the public key the
        permit was issued to, or None if invalid/expired/foreign.

        Template method: the range check runs HERE so no backend can skip
        it — an out-of-space wire permit must never reach storage (see
        ``permit_in_range``). Backends implement ``_validate_permit``."""
        if not permit_in_range(permit):
            return None
        return await self._validate_permit(broker, permit)

    @abc.abstractmethod
    async def _validate_permit(self, broker: BrokerIdentifier,
                               permit: int) -> Optional[bytes]:
        ...

    @abc.abstractmethod
    async def set_whitelist(self, users: List[bytes]) -> None: ...

    # -- user-slot directory (multi-host device planes) --------------------
    # The single-host mesh group keeps pk -> device-slot in process memory;
    # across hosts the mapping must rendezvous somewhere, and discovery is
    # already the cross-host registry (the reference moves the same facts in
    # its UserSync gossip, cdn-broker/src/tasks/broker/sync.rs). Backends
    # without a directory inherit the empty default: remote directs then
    # fall back to the host path.

    async def publish_user_slots(self, entries, ttl_s: float) -> None:
        """Publish this host's ``{public_key: slot}`` claims with a TTL;
        re-published every directory refresh (heartbeat-style)."""

    async def get_user_slots(self):
        """Return ``{public_key: (slot, published_ts)}`` for every live
        claim. Default: no directory."""
        return {}

    async def drop_user_slots(self, keys: List[bytes]) -> None:
        """Remove claims for departed users."""


    @abc.abstractmethod
    async def check_whitelist(self, user: bytes) -> bool:
        """True if ``user`` may connect; an EMPTY whitelist admits everyone
        (matching the reference's default-open posture for local runs)."""

    async def close(self) -> None:  # optional override
        return None
