"""Embedded discovery: SQLite-backed membership/permits/whitelist.

Capability parity with cdn-proto/src/discovery/embedded.rs:39-423 (+ schema
in cdn-proto/local_db/migrations.sql): same semantics as the Redis/KeyDB
implementation with explicit expiry pruning — ``brokers`` rows age out after
their heartbeat TTL, permits after theirs; whitelist is a plain key set and
an EMPTY whitelist admits everyone.

Used for local runs and single-process integration tests: every actor opens
the same SQLite file, which stands in for KeyDB exactly the way the Memory
transport stands in for the network (SURVEY.md §4).

Operations are synchronous sqlite3 under the hood (they are local,
microsecond-scale, and infrequent: heartbeats every 10 s, auth handshakes);
the async interface is kept so the Redis implementation can be truly async.
"""

from __future__ import annotations

import asyncio
import secrets
import sqlite3
import time
from typing import List, Optional

from pushcdn_tpu.proto.discovery.base import BrokerIdentifier, DiscoveryClient
from pushcdn_tpu.proto.error import ErrorKind, bail

# Cross-process write contention policy (ISSUE 12): sqlite raises
# OperationalError('database is locked') when another process holds the
# write lock past busy_timeout. Writes retry on this bounded schedule
# before surfacing a TYPED Error(CONNECTION) — never the raw sqlite3
# exception. Total budget (~0.75 s + busy_timeout per attempt) stays well
# under the 8 s chaos-outage hold, so a genuine discovery outage still
# fails loudly (heartbeat task-died events, admissions refused) instead
# of hanging. Tests shrink both knobs to keep the slow path fast.
LOCKED_RETRY_SCHEDULE = (0.05, 0.1, 0.2, 0.4)
BUSY_TIMEOUT_MS = 5000


def _is_locked(exc: sqlite3.OperationalError) -> bool:
    msg = str(exc).lower()
    return "locked" in msg or "busy" in msg


async def _locked_retry(op, what: str):
    """Run the synchronous sqlite write ``op`` with bounded backoff on
    lock contention; other OperationalErrors propagate unchanged."""
    for delay in LOCKED_RETRY_SCHEDULE:
        try:
            return op()
        except sqlite3.OperationalError as exc:
            if not _is_locked(exc):
                raise
        await asyncio.sleep(delay)
    try:
        return op()
    except sqlite3.OperationalError as exc:
        if not _is_locked(exc):
            raise
        bail(ErrorKind.CONNECTION, f"discovery store busy: {what}", exc)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS brokers (
    identifier TEXT PRIMARY KEY,
    num_connections INTEGER NOT NULL DEFAULT 0,
    expiry REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS permits (
    permit INTEGER PRIMARY KEY,
    broker TEXT NOT NULL,
    public_key BLOB NOT NULL,
    expiry REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS whitelist (
    public_key BLOB PRIMARY KEY
);
CREATE TABLE IF NOT EXISTS user_slots (
    public_key BLOB PRIMARY KEY,
    slot INTEGER NOT NULL,
    ts REAL NOT NULL,
    expiry REAL NOT NULL
);
"""


class Embedded(DiscoveryClient):
    """SQLite discovery client (parity ``Embedded``, embedded.rs:39-423)."""

    def __init__(self, path: str, identity: Optional[BrokerIdentifier],
                 global_permits: bool = False):
        self.path = path
        self.identity = identity
        # global_permits: permits redeemable at any broker (the reference's
        # `global-permits` cargo feature, threaded through discovery/auth)
        self.global_permits = global_permits
        # autocommit: every statement is its own WAL transaction, so no
        # connection can hold the cross-process write lock between event-
        # loop turns (python's legacy implicit transactions did, and a
        # second process then hits 'database is locked' past busy_timeout)
        self._db = sqlite3.connect(path, check_same_thread=False,
                                   isolation_level=None)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(f"PRAGMA busy_timeout={int(BUSY_TIMEOUT_MS)}")
        # Permits/heartbeats are ephemeral (30-60 s TTLs): losing the tail
        # of the WAL on power loss only forces reconnects, so skip the
        # per-commit fsync — it was most of the auth handshake's floor
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.executescript(_SCHEMA)
        self._db.commit()

    @classmethod
    async def new(cls, endpoint: str,
                  identity: Optional[BrokerIdentifier] = None,
                  global_permits: bool = False) -> "Embedded":
        """``endpoint`` is a filesystem path (or ":memory:" for throwaway)."""
        try:
            return cls(endpoint, identity, global_permits)
        except sqlite3.Error as exc:
            bail(ErrorKind.FILE, f"cannot open embedded discovery at {endpoint}", exc)

    # -- membership ---------------------------------------------------------

    def _prune(self) -> None:
        now = time.time()
        self._db.execute("DELETE FROM brokers WHERE expiry < ?", (now,))
        self._db.execute("DELETE FROM permits WHERE expiry < ?", (now,))
        self._db.commit()

    async def perform_heartbeat(self, num_connections: int,
                                heartbeat_expiry_s: float) -> None:
        if self.identity is None:
            bail(ErrorKind.PARSE, "heartbeat requires a broker identity")

        def write():
            self._db.execute(
                "INSERT INTO brokers (identifier, num_connections, expiry) "
                "VALUES (?, ?, ?) ON CONFLICT(identifier) DO UPDATE SET "
                "num_connections=excluded.num_connections, expiry=excluded.expiry",
                (str(self.identity), num_connections,
                 time.time() + heartbeat_expiry_s))
            self._db.commit()
        await _locked_retry(write, "heartbeat")

    async def deregister(self) -> None:
        if self.identity is None:
            return

        def write():
            self._db.execute("DELETE FROM brokers WHERE identifier = ?",
                             (str(self.identity),))
            self._db.commit()
        await _locked_retry(write, "deregister")

    async def get_other_brokers(self) -> List[BrokerIdentifier]:
        await _locked_retry(self._prune, "prune")
        me = str(self.identity) if self.identity else None
        rows = self._db.execute(
            "SELECT identifier FROM brokers").fetchall()
        return [BrokerIdentifier.from_string(r[0]) for r in rows
                if r[0] != me]

    async def get_with_least_connections(self) -> BrokerIdentifier:
        """Load = live connections + outstanding permits (parity
        redis.rs:139-167)."""
        await _locked_retry(self._prune, "prune")
        rows = self._db.execute(
            "SELECT b.identifier, b.num_connections + "
            " (SELECT COUNT(*) FROM permits p WHERE p.broker = b.identifier) "
            "FROM brokers b ORDER BY 2 ASC, b.identifier ASC").fetchall()
        if not rows:
            bail(ErrorKind.CONNECTION, "no live brokers in discovery")
        return BrokerIdentifier.from_string(rows[0][0])

    # -- permits ------------------------------------------------------------

    async def issue_permit(self, for_broker: BrokerIdentifier,
                           expiry_s: float, public_key: bytes) -> int:
        # permit semantics: 0=fail, 1=ack, >1=real permit (message.rs:338-341)
        while True:
            permit = secrets.randbits(62) + 2

            def write():
                self._db.execute(
                    "INSERT INTO permits (permit, broker, public_key, expiry) "
                    "VALUES (?, ?, ?, ?)",
                    (permit, str(for_broker), bytes(public_key),
                     time.time() + expiry_s))
                self._db.commit()
            try:
                await _locked_retry(write, "issue_permit")
                return permit
            except sqlite3.IntegrityError:
                continue  # permit collision: retry

    async def _validate_permit(self, broker: BrokerIdentifier,
                               permit: int) -> Optional[bytes]:
        """Redeem-and-delete (GETDEL parity, redis permit redemption);
        range-checked by the base-class template method."""
        await _locked_retry(self._prune, "prune")
        row = self._db.execute(
            "SELECT broker, public_key FROM permits WHERE permit = ?",
            (permit,)).fetchone()
        if row is None:
            return None
        if not self.global_permits and row[0] != str(broker):
            return None  # issued for a different broker

        def write():
            self._db.execute("DELETE FROM permits WHERE permit = ?", (permit,))
            self._db.commit()
        await _locked_retry(write, "validate_permit")
        return bytes(row[1])

    # -- whitelist ----------------------------------------------------------

    async def set_whitelist(self, users: List[bytes]) -> None:
        # the one compound write that must stay atomic under autocommit: a
        # reader between the DELETE and the INSERTs would see an empty
        # whitelist (= admit everyone)
        def write():
            self._db.execute("BEGIN IMMEDIATE")
            try:
                self._db.execute("DELETE FROM whitelist")
                self._db.executemany(
                    "INSERT OR IGNORE INTO whitelist (public_key) VALUES (?)",
                    [(bytes(u),) for u in users])
                self._db.execute("COMMIT")
            except BaseException:
                self._db.execute("ROLLBACK")
                raise
        await _locked_retry(write, "set_whitelist")
        # The whitelist is DURABLE access control (an empty table admits
        # everyone) — force the WAL to disk so synchronous=NORMAL's
        # skipped fsync (fine for ephemeral permits/heartbeats) can't
        # fail-open the broker after a power loss.
        self._db.execute("PRAGMA wal_checkpoint(TRUNCATE)")

    async def check_whitelist(self, user: bytes) -> bool:
        n = self._db.execute("SELECT COUNT(*) FROM whitelist").fetchone()[0]
        if n == 0:
            return True  # empty whitelist admits everyone
        row = self._db.execute(
            "SELECT 1 FROM whitelist WHERE public_key = ?",
            (bytes(user),)).fetchone()
        return row is not None

    # -- user-slot directory (multi-host device planes) ---------------------

    async def publish_user_slots(self, entries, ttl_s: float) -> None:
        now = time.time()
        # newest claim wins: a loser host's TTL re-publication must not
        # overwrite the winning host's newer claim (claim ts is fixed at
        # claim time; refreshes carry the same ts and still bump expiry)
        self._db.executemany(
            "INSERT INTO user_slots (public_key, slot, ts, expiry) "
            "VALUES (?, ?, ?, ?) ON CONFLICT(public_key) DO UPDATE SET "
            "slot=excluded.slot, ts=excluded.ts, expiry=excluded.expiry "
            "WHERE excluded.ts >= user_slots.ts",
            [(bytes(pk), int(slot), float(ts), now + ttl_s)
             for pk, (slot, ts) in entries.items()])

    async def get_user_slots(self):
        now = time.time()
        self._db.execute("DELETE FROM user_slots WHERE expiry < ?", (now,))
        rows = self._db.execute(
            "SELECT public_key, slot, ts FROM user_slots").fetchall()
        return {bytes(r[0]): (int(r[1]), float(r[2])) for r in rows}

    async def drop_user_slots(self, keys) -> None:
        self._db.executemany("DELETE FROM user_slots WHERE public_key = ?",
                             [(bytes(k),) for k in keys])

    async def close(self) -> None:
        self._db.close()
