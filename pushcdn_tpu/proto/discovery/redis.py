"""Redis/KeyDB discovery: the production membership store.

Capability parity with cdn-proto/src/discovery/redis.rs:38-327: atomic
heartbeat pipeline (set-membership + per-member expiry + load value),
least-connections scan including outstanding permit counts, GETDEL permit
redemption, whitelist set.

Gated: this environment ships no redis client library (and installing is
disallowed), so the import is lazy — ``Redis.new`` raises a clear error
when the ``redis`` package is missing, and the implementation below runs
unmodified once it is present. Note the reference actually requires KeyDB
(for ``EXPIREMEMBER``, redis.rs:94); we instead store one key per broker
with a plain TTL, which works on stock Redis as well.

Keys:
    broker:{identifier}      -> num_connections     (TTL = heartbeat expiry)
    permit:{permit}          -> broker|public_key   (TTL = permit expiry)
    whitelist                -> set of public keys
"""

from __future__ import annotations

import secrets
from typing import List, Optional

from pushcdn_tpu.proto.discovery.base import BrokerIdentifier, DiscoveryClient
from pushcdn_tpu.proto.error import ErrorKind, bail

_PREFIX_BROKER = "broker:"
_PREFIX_PERMIT = "permit:"
_KEY_WHITELIST = "whitelist"
_PREFIX_USLOT = "uslot:"


class Redis(DiscoveryClient):
    def __init__(self, client, identity: Optional[BrokerIdentifier],
                 global_permits: bool = False):
        self._client = client
        self.identity = identity
        self.global_permits = global_permits

    @classmethod
    async def new(cls, endpoint: str,
                  identity: Optional[BrokerIdentifier] = None,
                  global_permits: bool = False) -> "Redis":
        try:
            import redis.asyncio as aioredis  # lazy: not in this image
        except ImportError as exc:
            bail(ErrorKind.CONNECTION,
                 "the 'redis' package is not available in this environment; "
                 "use Embedded (SQLite) discovery instead", exc)
        client = aioredis.from_url(endpoint, decode_responses=False)
        return cls(client, identity, global_permits)

    async def perform_heartbeat(self, num_connections: int,
                                heartbeat_expiry_s: float) -> None:
        if self.identity is None:
            bail(ErrorKind.PARSE, "heartbeat requires a broker identity")
        # atomic pipeline (parity redis.rs:86-112)
        pipe = self._client.pipeline(transaction=True)
        pipe.set(f"{_PREFIX_BROKER}{self.identity}", num_connections,
                 ex=int(heartbeat_expiry_s))
        await pipe.execute()

    async def deregister(self) -> None:
        if self.identity is None:
            return
        await self._client.delete(f"{_PREFIX_BROKER}{self.identity}")

    async def get_other_brokers(self) -> List[BrokerIdentifier]:
        me = f"{_PREFIX_BROKER}{self.identity}" if self.identity else None
        out = []
        async for key in self._client.scan_iter(match=f"{_PREFIX_BROKER}*"):
            k = key.decode() if isinstance(key, bytes) else key
            if k != me:
                out.append(BrokerIdentifier.from_string(k[len(_PREFIX_BROKER):]))
        return out

    async def get_with_least_connections(self) -> BrokerIdentifier:
        best, best_load = None, None
        async for key in self._client.scan_iter(match=f"{_PREFIX_BROKER}*"):
            k = key.decode() if isinstance(key, bytes) else key
            ident = k[len(_PREFIX_BROKER):]
            raw = await self._client.get(key)
            conns = int(raw or 0)
            # outstanding permits count toward load (redis.rs:139-167)
            permits = 0
            async for pkey in self._client.scan_iter(match=f"{_PREFIX_PERMIT}*"):
                val = await self._client.get(pkey)
                if val is not None and val.split(b"|", 1)[0].decode() == ident:
                    permits += 1
            load = conns + permits
            if best_load is None or (load, ident) < (best_load, best):
                best, best_load = ident, load
        if best is None:
            bail(ErrorKind.CONNECTION, "no live brokers in discovery")
        return BrokerIdentifier.from_string(best)

    async def issue_permit(self, for_broker: BrokerIdentifier,
                           expiry_s: float, public_key: bytes) -> int:
        while True:
            permit = secrets.randbits(62) + 2
            ok = await self._client.set(
                f"{_PREFIX_PERMIT}{permit}",
                str(for_broker).encode() + b"|" + bytes(public_key),
                ex=int(expiry_s), nx=True)
            if ok:
                return permit

    async def _validate_permit(self, broker: BrokerIdentifier,
                               permit: int) -> Optional[bytes]:
        # range-checked by the base-class template method
        raw = await self._client.getdel(f"{_PREFIX_PERMIT}{permit}")
        if raw is None:
            return None
        issued_for, _, public_key = raw.partition(b"|")
        if not self.global_permits and issued_for.decode() != str(broker):
            return None
        return bytes(public_key)

    async def set_whitelist(self, users: List[bytes]) -> None:
        pipe = self._client.pipeline(transaction=True)
        pipe.delete(_KEY_WHITELIST)
        if users:
            pipe.sadd(_KEY_WHITELIST, *[bytes(u) for u in users])
        await pipe.execute()

    async def check_whitelist(self, user: bytes) -> bool:
        if await self._client.scard(_KEY_WHITELIST) == 0:
            return True
        return bool(await self._client.sismember(_KEY_WHITELIST, bytes(user)))

    # -- user-slot directory (multi-host device planes) ---------------------

    async def publish_user_slots(self, entries, ttl_s: float) -> None:
        # newest claim wins (read-compare-write; the tiny race window is
        # closed by the next refresh, since the loser's claim ts never
        # grows while the winner's republication carries the newer one)
        names = {f"{_PREFIX_USLOT}{bytes(pk).hex()}": (pk, v)
                 for pk, v in entries.items()}
        current = await self._client.mget(list(names)) if names else []
        pipe = self._client.pipeline(transaction=True)
        for (key, (pk, (slot, ts))), raw in zip(names.items(), current):
            if raw is not None:
                v = raw.decode() if isinstance(raw, bytes) else raw
                if float(v.split(":", 1)[1]) > float(ts):
                    continue  # a newer claim exists elsewhere
            pipe.set(key, f"{int(slot)}:{float(ts)}", ex=max(1, int(ttl_s)))
        await pipe.execute()

    async def get_user_slots(self):
        names = []
        async for key in self._client.scan_iter(match=f"{_PREFIX_USLOT}*"):
            names.append(key.decode() if isinstance(key, bytes) else key)
        if not names:
            return {}
        out = {}
        # one MGET for the lot: the directory refresh runs on every host
        # every ~0.5 s, so per-key round trips would dominate Redis load
        values = await self._client.mget(names)
        for k, raw in zip(names, values):
            if raw is None:
                continue
            v = raw.decode() if isinstance(raw, bytes) else raw
            slot_s, ts_s = v.split(":", 1)
            out[bytes.fromhex(k[len(_PREFIX_USLOT):])] = (int(slot_s),
                                                          float(ts_s))
        return out

    async def drop_user_slots(self, keys) -> None:
        if keys:
            await self._client.delete(
                *(f"{_PREFIX_USLOT}{bytes(k).hex()}" for k in keys))

    async def close(self) -> None:
        await self._client.aclose()
