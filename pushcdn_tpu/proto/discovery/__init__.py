"""Discovery: membership, load, permits, whitelist.

Capability parity with cdn-proto/src/discovery/ (SURVEY.md §1 L5).
"""

from pushcdn_tpu.proto.discovery.base import (  # noqa: F401
    BrokerIdentifier,
    DiscoveryClient,
)
from pushcdn_tpu.proto.discovery.embedded import Embedded  # noqa: F401
