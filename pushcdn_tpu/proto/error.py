"""Error model.

Capability parity with the reference's 9-variant error enum that
distinguishes reconnect-worthy from fatal errors
(cdn-proto/src/error.rs:21-72). We keep one exception type carrying an
``ErrorKind`` so callers can branch on kind without a deep class hierarchy,
plus ``bail``/``bail_option`` helpers mirroring the reference's macros
(error.rs contains `bail!` / `bail_option!` / `parse_endpoint!`).
"""

from __future__ import annotations

import enum
import re
from typing import NoReturn, Optional, TypeVar

T = TypeVar("T")

# typed retry-after hint embedded in shed contexts: "...; retry-after=2.5"
_RETRY_AFTER = re.compile(r"retry-after=([0-9]+(?:\.[0-9]+)?)")


def retry_after_hint(context: str) -> Optional[float]:
    """Parse the ``retry-after=<seconds>`` hint a shedding server appends
    to its rejection context. Returns None when absent/unparseable — the
    hint is advisory; clients fall back to plain jittered backoff."""
    if not context:
        return None
    m = _RETRY_AFTER.search(context)
    if m is None:
        return None
    try:
        return float(m.group(1))
    except ValueError:  # pragma: no cover - regex guarantees a float
        return None


class ErrorKind(enum.Enum):
    """What failed — used to decide reconnect vs fatal vs drop-message."""

    CONNECTION = "connection"      # transport-level send/recv failure (reconnect-worthy)
    AUTHENTICATION = "authentication"  # handshake rejected (re-auth via marshal)
    SERIALIZE = "serialize"        # could not encode a message
    DESERIALIZE = "deserialize"    # malformed inbound frame (disconnect peer)
    CRYPTO = "crypto"              # sign/verify failure
    PARSE = "parse"                # endpoint / config parse failure
    FILE = "file"                  # file I/O (CA certs, embedded DB path)
    EXCEEDED_SIZE = "exceeded_size"  # frame larger than MAX_MESSAGE_SIZE
    TIMEOUT = "timeout"            # I/O deadline elapsed
    SHED = "shed"                  # server load-shed a request (back off;
    #                                the connection itself is still live)


class Error(Exception):
    """The single framework error type.

    ``kind`` drives policy: ``CONNECTION``/``TIMEOUT`` are reconnect-worthy
    for the elastic client; ``AUTHENTICATION`` means go back through the
    marshal; ``DESERIALIZE`` means disconnect the sending peer.
    """

    def __init__(self, kind: ErrorKind, message: str, cause: Optional[BaseException] = None):
        super().__init__(f"{kind.value}: {message}")
        self.kind = kind
        self.message = message
        self.cause = cause
        # typed backoff hint (seconds) for SHED errors — parsed from the
        # server's context by retry_after_hint(); None when absent
        self.retry_after_s: Optional[float] = retry_after_hint(message) \
            if kind is ErrorKind.SHED else None

    @property
    def is_reconnectable(self) -> bool:
        """Errors the elastic client heals by re-dialing (vs giving up)."""
        return self.kind in (ErrorKind.CONNECTION, ErrorKind.TIMEOUT)


def bail(kind: ErrorKind, message: str, cause: Optional[BaseException] = None) -> NoReturn:
    """Raise an :class:`Error`, chaining ``cause`` if given."""
    err = Error(kind, message, cause)
    if cause is not None:
        raise err from cause
    raise err


def bail_option(value: Optional[T], kind: ErrorKind, message: str) -> T:
    """Unwrap ``value`` or raise — analog of the reference's `bail_option!`."""
    if value is None:
        bail(kind, message)
    return value


def parse_endpoint(endpoint: str) -> tuple[str, int]:
    """Split ``"host:port"``; analog of the reference's `parse_endpoint!`."""
    host, sep, port = endpoint.rpartition(":")
    if not sep or not host:
        bail(ErrorKind.PARSE, f"malformed endpoint {endpoint!r}, want 'host:port'")
    try:
        return host, int(port)
    except ValueError as exc:
        bail(ErrorKind.PARSE, f"malformed port in endpoint {endpoint!r}", exc)
