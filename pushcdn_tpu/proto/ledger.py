"""Frame-fate conservation ledger (ISSUE 20 tentpole).

Every frame instance the data plane takes responsibility for is accounted
from ingress to exactly one terminal **fate**:

- ``delivered`` — written toward a local user (host writer dequeue, or a
  pumped send-CQE counted in C and folded in by delta);
- ``relayed``  — written toward a peer broker or handed to a sibling
  shard's ring (the frame is now the next hop's responsibility);
- ``dropped``  — any counted loss, labeled with a ``reason`` from the
  closed taxonomy below.

The taxonomy is CLOSED: :func:`record_fate` refuses a ``(fate, reason)``
pair not present in :data:`TAXONOMY`, and the exhaustiveness test
(tests/test_ledger.py) greps the tree so every instrumented call site
uses a registered reason and every registered reason has a call site —
a new drop path cannot ship uncounted.

Conservation identity (the audited invariant): over the writer-queue
plane,

    queued == delivered + relayed + queue_drops + in_queue

where ``queued`` is counted at successful send-queue insert (real frame
counts ride every writer entry stamp), the fates are counted at dequeue /
drain, and ``in_queue`` is *derived* (queued − fates). The auditor
cross-checks the derived value against an actual walk of every live
connection's send queue; a mismatch that persists across two quiescent
ticks (no counter moved in between, so it cannot be in-flight skew) is a
conservation violation: it increments ``cdn_conservation_violations``,
records a flight-recorder event, and flips the ``/readyz``
``conservation`` check for ``PUSHCDN_CONSERVATION_READY_S``.

Pumped frames never enter a Python writer queue: the native telemetry
fold (metrics.update_native_telemetry) credits ``queued`` and the
terminal fate (``delivered/pumped`` or ``dropped/pump_peer_poison``) in
the same delta, so the identity holds with the pump's in-flight window
invisible by construction (bounded by PUMP_CHAIN_MAX × peers).

Per-link conservation: routing decisions toward a broker peer bump the
monotone ``(peer, class)`` ``link_sent`` table (decision time is where
the per-frame class is exact and both ends classify identically), and
the receive loops bump ``link_recv`` per upstream with the same
frame-derived rule (Broadcast → topic class, Direct → live, any other
kind → control). Sheets are exchanged mesh-wide as
``LedgerSync`` (wire kind 13) over the existing sync task — no per-frame
wire overhead — so each hop exports ``cdn_link_deficit{peer,class}``
against its upstream's claim, and ``scripts/cdn_top.py --audit`` merges
every process's ``/debug/ledger`` into one cluster balance sheet.

Loss-budget SLOs: :class:`SloEngine` turns the ledger's loss counters
into multi-window burn rates (``cdn_slo_burn_rate{slo,window}``) —
burn > 1 means the class is spending its error budget faster than the
window allows. Knobs: ``PUSHCDN_SLO_WINDOWS`` (seconds, comma list),
``PUSHCDN_SLO_LOSS_BUDGET`` (+ per-class ``_CONTROL``/``_CONSENSUS``/
``_LIVE``/``_BULK`` overrides), ``PUSHCDN_SLO_DELIVERY_P99_MS``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Tuple

from pushcdn_tpu.proto import metrics as metrics_mod

logger = logging.getLogger("pushcdn.ledger")

# class axis: the four flowclass classes + "none" (a frame with no route:
# the plan writes class 255, bincount excludes it — the ledger still
# gives the instance a fate)
CLASS_LABELS = ("control", "consensus", "live", "bulk", "none")
NCLS = len(CLASS_LABELS)
IDX_NONE = 4


def class_index(cls: int) -> int:
    """Map a wire/plan class value to the ledger's class axis (255 and
    anything out of range → "none")."""
    return cls if 0 <= cls < 4 else IDX_NONE


# -- the closed fate taxonomy ------------------------------------------------
# (fate, reason) -> (in conservation identity?, description). The identity
# column marks fates counted against writer-queue `queued`; decision-time
# and off-path fates (retained copies, malformed ingress) sit outside it.
TAXONOMY: Dict[Tuple[str, str], Tuple[bool, str]] = {
    ("delivered", "egress"): (True, "writer dequeue toward a local user"),
    ("delivered", "pumped"): (True, "native pump send-CQE (C fold)"),
    ("relayed", "mesh"): (True, "writer dequeue toward a peer broker"),
    ("relayed", "shard_ring"): (False, "handed to a sibling shard's ring"),
    ("dropped", "writer_teardown"): (True, "send queue drained at close"),
    ("dropped", "conn_poisoned"): (True, "send queue drained on I/O error"),
    ("dropped", "send_failed"): (True, "failure-is-removal drain"),
    ("dropped", "parting_expiry"): (True, "parting-grace chase expired"),
    ("dropped", "pump_peer_poison"): (True, "pumped runs abandoned in C"),
    ("dropped", "admission_shed"): (False, "admission plane refused work"),
    ("dropped", "relay_shed"): (False, "shard relay budget exceeded"),
    ("dropped", "no_route"): (False, "Direct with unknown/stale recipient"),
    ("dropped", "no_interest"): (False, "Broadcast with zero recipients"),
    ("dropped", "malformed"): (False, "undecodable ingress frame"),
    ("dropped", "retention_evict"): (False, "retained copy evicted"),
}

# fates summed against `queued` in the conservation identity
IDENTITY_FATES = frozenset(k for k, (in_id, _) in TAXONOMY.items() if in_id)

# dropped reasons that count as LOSS for the SLO loss budget (benign
# fates — nobody wanted the frame, or it never decoded, or it was a
# retained *copy* — don't burn budget)
LOSS_REASONS = frozenset(
    r for (f, r) in TAXONOMY if f == "dropped"
    and r not in ("no_interest", "malformed", "retention_evict"))

FRAME_FATE = metrics_mod.Counter(
    "cdn_frame_fate",
    "Terminal fate of every frame instance the data plane took "
    "responsibility for (closed taxonomy; see proto/ledger.py)",
    labels=("fate", "reason", "class"))

CONSERVATION_VIOLATIONS = metrics_mod.Counter(
    "cdn_conservation_violations",
    "Audited conservation failures: frames vanished from the writer "
    "plane with no counted fate (quiescent ledger mismatch)")

LINK_DEFICIT = metrics_mod.Gauge(
    "cdn_link_deficit",
    "Frames an upstream broker claims it sent us minus frames we "
    "counted received from it (>0 past the in-flight window = loss on "
    "the link)",
    labels=("peer", "class"))

SLO_BURN = metrics_mod.Gauge(
    "cdn_slo_burn_rate",
    "Error-budget burn rate per SLO and window (>1 = burning faster "
    "than the budget allows; loss_<class> = frame loss vs "
    "PUSHCDN_SLO_LOSS_BUDGET, delivery_p99_<class> = writer-queue p99 "
    "vs PUSHCDN_SLO_DELIVERY_P99_MS)",
    labels=("slo", "window"))


class Ledger:
    """Process-local balance sheet. Event-loop-thread writers only (the
    native pump's counters arrive via the single-threaded telemetry
    fold); plain int math — the hot cost is one dict lookup + adds per
    writer ENTRY (a whole batch), never per frame."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("PUSHCDN_LEDGER", "1") != "0"
        # incarnation epoch: a respawned broker reuses its canonical
        # identifier, so per-link counters are meaningful only within one
        # (sender incarnation, receiver incarnation) pair — the sheet
        # carries this stamp and note_peer_sheet resets a link's tables
        # when the peer's epoch changes
        self.boot = time.time()
        self.queued = [0] * NCLS           # writer-queue inserts
        self.ingress = [0] * NCLS          # frames accepted from peers
        # (fate, reason) -> per-class counts
        self.fates: Dict[Tuple[str, str], List[int]] = {}
        # monotone per-link tables: peer identifier -> per-class counts
        self.link_sent: Dict[str, List[int]] = {}
        self.link_recv: Dict[str, List[int]] = {}
        # peers' LedgerSync sheets: identifier -> dict snapshot, and the
        # boot epoch each sheet last carried (see ``boot`` above)
        self.peer_sheets: Dict[str, dict] = {}
        self._peer_boots: Dict[str, float] = {}
        # cached cdn_frame_fate children
        self._fate_children: Dict[Tuple[str, str, int], object] = {}
        # auditor state
        self.my_ident = ""              # set when the auditor starts
        self.violations = 0
        self.last_violation_at: Optional[float] = None
        self._last_totals: Optional[tuple] = None
        self._last_mismatch = False

    # -- recording ----------------------------------------------------------

    def note_queued(self, cls: int, n: int = 1) -> None:
        if n:
            self.queued[class_index(cls)] += n

    def note_ingress(self, cls: int, n: int = 1,
                     peer: Optional[str] = None) -> None:
        if not n:
            return
        i = class_index(cls)
        self.ingress[i] += n
        if peer is not None:
            row = self.link_recv.get(peer)
            if row is None:
                row = self.link_recv[peer] = [0] * NCLS
            row[i] += n

    def record_fate(self, fate: str, reason: str, cls: int,
                    n: int = 1) -> None:
        if not n:
            return
        key = (fate, reason)
        if key not in TAXONOMY:
            raise ValueError(f"unregistered frame fate {key!r} — add it to "
                             "proto.ledger.TAXONOMY")
        i = class_index(cls)
        row = self.fates.get(key)
        if row is None:
            row = self.fates[key] = [0] * NCLS
        row[i] += n
        child = self._fate_children.get((fate, reason, i))
        if child is None:
            child = FRAME_FATE.labels(**{"fate": fate, "reason": reason,
                                         "class": CLASS_LABELS[i]})
            self._fate_children[(fate, reason, i)] = child
        child.inc(n)

    def note_link_sent(self, peer: str, cls: int, n: int = 1) -> None:
        """Monotone per-link sent table, counted at the routing decision
        (where the per-frame class is exact) — in a teardown-free run
        this equals the peer's ``link_recv`` from us once in-flight
        drains; on link death the residual deficit is exactly the frames
        the teardown drop fates + the wire swallowed (what cdn_top
        --audit attributes to the dead peer)."""
        if not n:
            return
        row = self.link_sent.get(peer)
        if row is None:
            row = self.link_sent[peer] = [0] * NCLS
        row[class_index(cls)] += n

    # -- balance sheet ------------------------------------------------------

    def identity_fate_totals(self) -> List[int]:
        out = [0] * NCLS
        for key in IDENTITY_FATES:
            row = self.fates.get(key)
            if row is not None:
                for i, v in enumerate(row):
                    out[i] += v
        return out

    def derived_in_queue(self) -> List[int]:
        fates = self.identity_fate_totals()
        return [q - f for q, f in zip(self.queued, fates)]

    def walk_live_queues(self) -> int:
        """Actual frames sitting in live connections' send queues right
        now (the stamp's real-frame count; event-loop context only)."""
        from pushcdn_tpu.proto.transport import base as base_mod
        total = 0
        for conn in list(base_mod.LIVE_CONNECTIONS):
            try:
                for item in list(conn._send_q._queue):
                    if isinstance(item, tuple) and len(item) > 2 \
                            and item[2] is not None:
                        total += item[2][4]
            except Exception:
                continue
        return total

    def check_conservation(self,
                           in_queue_actual: Optional[int] = None) -> dict:
        """One auditor tick. Returns the balance sheet; flags (and
        counts) a violation per the quiescence rule documented in the
        module docstring."""
        if in_queue_actual is None:
            in_queue_actual = self.walk_live_queues()
        derived = self.derived_in_queue()
        total_derived = sum(derived)
        totals = (tuple(self.queued),
                  tuple(sorted((k, tuple(v))
                               for k, v in self.fates.items())))
        # BOTH mismatch shapes (derived != actual walk, or a negative
        # derived balance) are gated on quiescence: live traffic
        # legitimately interleaves enqueue/dequeue accounting within a
        # tick, so only a discrepancy that survives two consecutive
        # ticks with no counter movement in between is a violation.
        mismatch = (total_derived != in_queue_actual
                    or any(d < 0 for d in derived))
        quiescent = totals == self._last_totals
        violation = mismatch and quiescent and self._last_mismatch
        self._last_totals = totals
        self._last_mismatch = mismatch and quiescent
        if violation:
            self.violations += 1
            self.last_violation_at = time.monotonic()
            CONSERVATION_VIOLATIONS.inc()
            detail = (f"queued={sum(self.queued)} "
                      f"fates={sum(self.identity_fate_totals())} "
                      f"derived_in_queue={total_derived} "
                      f"actual_in_queue={in_queue_actual}")
            from pushcdn_tpu.proto import flightrec
            flightrec.task_recorder().record("conservation-violation",
                                             detail, abnormal=True)
            logger.warning("conservation violation: %s", detail)
        return {
            "derived_in_queue": derived,
            "in_queue_actual": in_queue_actual,
            "violation": violation,
        }

    def conservation_check(self):
        """/readyz check: unready while a violation is recent."""
        window = float(os.environ.get("PUSHCDN_CONSERVATION_READY_S",
                                      "120") or 120)
        if self.last_violation_at is None:
            return True, f"balanced ({self.violations} violations ever)"
        age = time.monotonic() - self.last_violation_at
        if age < window:
            return False, (f"conservation violation {age:.0f}s ago "
                           f"({self.violations} total)")
        return True, f"last violation {age:.0f}s ago"

    # -- mesh exchange ------------------------------------------------------

    def sheet(self, ident: str = "") -> dict:
        """This process's exchangeable balance sheet (LedgerSync payload
        and the /debug/ledger body's ``local`` section)."""
        return {
            "ident": ident,
            "ts": time.time(),
            "boot": self.boot,
            "queued": dict(zip(CLASS_LABELS, self.queued)),
            "ingress": dict(zip(CLASS_LABELS, self.ingress)),
            "fates": {f"{fate}/{reason}": dict(zip(CLASS_LABELS, row))
                      for (fate, reason), row in sorted(self.fates.items())},
            "in_queue_derived": dict(zip(CLASS_LABELS,
                                         self.derived_in_queue())),
            "link_sent": {p: dict(zip(CLASS_LABELS, row))
                          for p, row in sorted(self.link_sent.items())},
            "link_recv": {p: dict(zip(CLASS_LABELS, row))
                          for p, row in sorted(self.link_recv.items())},
            "violations": self.violations,
        }

    def reset_link(self, ident: str) -> None:
        """A (re)formed mesh link starts a fresh conservation epoch for
        ``ident``: per-link tables compare counters from ONE link
        incarnation at both ends, so a previous connection's residual
        (already audited — and attributed — while the link was down) must
        not bleed into the new link's balance. Clearing the remembered
        boot epoch keeps this composable with :meth:`note_peer_sheet`'s
        restart detection (the next sheet re-anchors, no double reset)."""
        self.link_sent.pop(ident, None)
        self.link_recv.pop(ident, None)
        self.peer_sheets.pop(ident, None)
        self._peer_boots.pop(ident, None)

    def note_peer_sheet(self, ident: str, sheet: dict) -> None:
        if not isinstance(sheet, dict):
            return
        boot = sheet.get("boot")
        last = self._peer_boots.get(ident)
        if isinstance(boot, (int, float)):
            if last is not None and boot != last:
                # the peer restarted under the same identifier: our
                # sent/recv counters toward the DEAD incarnation don't
                # balance against the fresh one's zeroed tables — start
                # a new conservation epoch for this link (the residual
                # was auditable, and attributed, while the peer was down)
                self.link_sent.pop(ident, None)
                self.link_recv.pop(ident, None)
                logger.info("ledger: peer %s restarted (epoch %.3f -> "
                            "%.3f); link tables reset", ident, last, boot)
            self._peer_boots[ident] = boot
        self.peer_sheets[ident] = sheet

    def update_link_deficits(self, my_ident: str) -> None:
        """Export cdn_link_deficit from each upstream's claim: what peer
        P says it sent us minus what we counted received from P."""
        for peer, sheet in self.peer_sheets.items():
            claimed = sheet.get("link_sent", {}).get(my_ident)
            if claimed is None:
                continue
            got = self.link_recv.get(peer, [0] * NCLS)
            for i, label in enumerate(CLASS_LABELS):
                d = int(claimed.get(label, 0)) - got[i]
                if d or label in claimed:
                    LINK_DEFICIT.labels(peer=peer,
                                        **{"class": label}).set(d)


LEDGER = Ledger()


# module-level fast paths (what the transport/routing hot sites call)
def note_queued(cls: int, n: int = 1) -> None:
    if LEDGER.enabled:
        LEDGER.note_queued(cls, n)


def note_ingress(cls: int, n: int = 1, peer: Optional[str] = None) -> None:
    if LEDGER.enabled:
        LEDGER.note_ingress(cls, n, peer)


def record_fate(fate: str, reason: str, cls: int, n: int = 1) -> None:
    if LEDGER.enabled:
        LEDGER.record_fate(fate, reason, cls, n)


def note_link_sent(peer: str, cls: int, n: int = 1) -> None:
    if LEDGER.enabled:
        LEDGER.note_link_sent(peer, cls, n)


def reset_link(peer: str) -> None:
    if LEDGER.enabled:
        LEDGER.reset_link(peer)


def on_dequeued(cls: int, n: int, peer: Optional[str] = None) -> None:
    """Writer dequeue: the frame(s) are being written — delivered toward
    a user, or relayed toward a peer broker (``peer`` set)."""
    if not LEDGER.enabled or not n:
        return
    if peer is not None:
        LEDGER.record_fate("relayed", "mesh", cls, n)
    else:
        LEDGER.record_fate("delivered", "egress", cls, n)


def on_transit(cls: int, n: int = 1, peer: Optional[str] = None) -> None:
    """Inline write path: queued and dequeued in one synchronous step."""
    if LEDGER.enabled and n:
        LEDGER.note_queued(cls, n)
        on_dequeued(cls, n, peer)


def reset_for_tests() -> None:
    global LEDGER
    LEDGER = Ledger()


# -- SLO burn-rate engine ----------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class SloEngine:
    """Multi-window burn rates over the ledger's loss counters (and,
    when targeted, the writer-queue delay p99). Ticked by the auditor;
    ``now`` is injectable for the seeded tests."""

    def __init__(self, ledger: Optional[Ledger] = None) -> None:
        self.ledger = ledger if ledger is not None else LEDGER
        raw = os.environ.get("PUSHCDN_SLO_WINDOWS", "") or "60,300"
        self.windows: List[float] = []
        for part in raw.split(","):
            part = part.strip()
            if part:
                try:
                    self.windows.append(float(part))
                except ValueError:
                    pass
        if not self.windows:
            self.windows = [60.0, 300.0]
        base = _env_float("PUSHCDN_SLO_LOSS_BUDGET", 1e-3)
        self.loss_budget = [
            _env_float(f"PUSHCDN_SLO_LOSS_BUDGET_{label.upper()}", base)
            for label in CLASS_LABELS[:4]]
        # 0 disables the delivery-p99 SLO
        self.p99_target_s = _env_float("PUSHCDN_SLO_DELIVERY_P99_MS",
                                       0.0) / 1e3
        self._samples: List[tuple] = []   # (t, attempts[4], losses[4], hist)

    def _loss_counts(self) -> List[int]:
        out = [0] * 4
        for (fate, reason), row in self.ledger.fates.items():
            if fate == "dropped" and reason in LOSS_REASONS:
                for i in range(4):
                    out[i] += row[i]
        return out

    def _attempt_counts(self) -> List[int]:
        """Delivery attempts = terminal fates inside the loss universe
        (delivered + relayed + counted losses)."""
        out = self._loss_counts()
        for (fate, _reason), row in self.ledger.fates.items():
            if fate in ("delivered", "relayed"):
                for i in range(4):
                    out[i] += row[i]
        return out

    @staticmethod
    def _hist_snapshot() -> list:
        out = []
        for child in metrics_mod.WRITER_QUEUE_DELAY_CLS:
            out.append((tuple(child.counts), child.total, child.buckets))
        return out

    @staticmethod
    def _p99_of_delta(before, after) -> Optional[float]:
        (c0, t0, buckets), (c1, t1, _) = before, after
        n = t1 - t0
        if n <= 0:
            return None
        target = 0.99 * n
        cum = 0
        for i, b in enumerate(buckets):
            cum += c1[i] - c0[i]
            if cum >= target:
                return b
        return buckets[-1] * 2  # +Inf bucket: beyond the last bound

    def tick(self, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        sample = (now, self._attempt_counts(), self._loss_counts(),
                  self._hist_snapshot())
        self._samples.append(sample)
        horizon = now - max(self.windows) - 1.0
        while len(self._samples) > 2 and self._samples[1][0] <= horizon:
            self._samples.pop(0)
        for w in self.windows:
            # oldest sample inside the window (fall back to the oldest
            # held — short uptimes still burn against what we have)
            base = self._samples[0]
            for s in self._samples:
                if s[0] >= now - w:
                    base = s
                    break
            wl = f"{int(w)}s"
            for i, label in enumerate(CLASS_LABELS[:4]):
                attempts = sample[1][i] - base[1][i]
                losses = sample[2][i] - base[2][i]
                rate = (losses / attempts) if attempts > 0 else 0.0
                burn = rate / self.loss_budget[i] \
                    if self.loss_budget[i] > 0 else 0.0
                SLO_BURN.labels(slo=f"loss_{label}", window=wl).set(burn)
                if self.p99_target_s > 0:
                    p99 = self._p99_of_delta(base[3][i], sample[3][i])
                    burn99 = (p99 / self.p99_target_s) if p99 else 0.0
                    SLO_BURN.labels(slo=f"delivery_p99_{label}",
                                    window=wl).set(burn99)


# -- the supervised auditor task ---------------------------------------------

async def run_auditor(interval_s: Optional[float] = None,
                      my_ident: str = "") -> None:
    """Continuous conservation auditor + SLO engine tick (spawned via
    metrics.supervised by the broker)."""
    import asyncio
    if interval_s is None:
        interval_s = _env_float("PUSHCDN_AUDIT_INTERVAL_S", 1.0)
    if my_ident:
        LEDGER.my_ident = my_ident
    engine = SloEngine()
    while True:
        await asyncio.sleep(interval_s)
        LEDGER.check_conservation()
        engine.tick()
        if my_ident:
            LEDGER.update_link_deficits(my_ident)


def ledger_route(params: dict) -> dict:
    """``GET /debug/ledger``: this process's sheet + the peers' sheets it
    has heard over LedgerSync (cdn_top --audit merges these)."""
    ident = params.get("ident", [""])
    ident = ident[0] if isinstance(ident, list) else str(ident)
    return {
        "local": LEDGER.sheet(ident or LEDGER.my_ident),
        "peers": LEDGER.peer_sheets,
        "conservation": {
            "violations": LEDGER.violations,
            "in_queue_derived": sum(LEDGER.derived_in_queue()),
        },
    }
