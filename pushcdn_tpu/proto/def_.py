"""Run configuration: explicit wiring of scheme × transport × discovery ×
topic space × message hooks.

Capability parity with cdn-proto/src/def.rs:31-168. The reference does this
with compile-time trait generics (``RunDef``/``ConnectionDef``) and cargo
features; here it is plain config objects — everything the reference selects
at compile time is selected by constructing one of these (SURVEY.md §7:
"everything it does with trait generics becomes a small typed registry").
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional, Type

from pushcdn_tpu.proto.crypto.signature import DEFAULT_SCHEME, SignatureScheme
from pushcdn_tpu.proto.discovery.base import DiscoveryClient
from pushcdn_tpu.proto.discovery.embedded import Embedded
from pushcdn_tpu.proto.discovery.redis import Redis
from pushcdn_tpu.proto.message import Message
from pushcdn_tpu.proto.topic import TEST_TOPIC_SPACE, TopicSpace
from pushcdn_tpu.proto.transport.base import Protocol
from pushcdn_tpu.proto.transport.memory import Memory
from pushcdn_tpu.proto.transport.tcp import Tcp
from pushcdn_tpu.proto.transport.tcp_tls import TcpTls


class HookResult(enum.Enum):
    """What a message hook decided (parity ``HookResult``, def.rs:70-97)."""

    PROCESS = "process"        # route normally
    SKIP = "skip"              # drop silently
    DISCONNECT = "disconnect"  # drop and kick the sender


# hook(sender_id, message) -> HookResult; sender_id is the user public key
# or broker identity string (parity MessageHookDef's identifier).
MessageHook = Callable[[object, Message], HookResult]


def no_hook(_sender, _message) -> HookResult:
    return HookResult.PROCESS


@dataclass
class ConnectionDef:
    """One edge's wiring: transport × signature scheme × hook
    (parity def.rs:62-66)."""

    protocol: Type[Protocol]
    scheme: Type[SignatureScheme] = DEFAULT_SCHEME
    hook: MessageHook = no_hook


@dataclass
class RunDef:
    """A full deployment definition (parity def.rs:54-59): how brokers talk
    to each other, how users talk to brokers, which discovery store, which
    topic space, and feature flags that were cargo features in the
    reference."""

    broker_def: ConnectionDef
    user_def: ConnectionDef
    discovery: Type[DiscoveryClient]
    topics: TopicSpace = field(default_factory=lambda: TEST_TOPIC_SPACE)
    # reference cargo features, now runtime flags:
    global_permits: bool = False        # permits valid at any broker
    strong_consistency: bool = True     # push syncs immediately on user join
                                        # (broker default feature)


def production_run_def(topics: Optional[TopicSpace] = None) -> RunDef:
    """Parity ``ProductionRunDef`` (def.rs:101-136): BLS-over-BN254 keys,
    broker↔broker plain TCP, user↔broker TCP+TLS, Redis/KeyDB discovery.
    Falls back to Ed25519 if the native BLS library can't compile on this
    host (the seam keeps callers agnostic)."""
    from pushcdn_tpu.proto.crypto.signature import BlsBn254Scheme
    scheme = BlsBn254Scheme if BlsBn254Scheme.available() else DEFAULT_SCHEME
    return RunDef(
        broker_def=ConnectionDef(protocol=Tcp, scheme=scheme),
        user_def=ConnectionDef(protocol=TcpTls, scheme=scheme),
        discovery=Redis,
        topics=topics or TopicSpace.range(256),
    )


def testing_run_def(broker_protocol: Type[Protocol] = Memory,
                    user_protocol: Type[Protocol] = Memory,
                    topics: Optional[TopicSpace] = None,
                    scheme: Type[SignatureScheme] = DEFAULT_SCHEME) -> RunDef:
    """Parity ``TestingRunDef<B,U>`` (def.rs:140-159): generic transports +
    Embedded (SQLite) discovery."""
    return RunDef(
        broker_def=ConnectionDef(protocol=broker_protocol, scheme=scheme),
        user_def=ConnectionDef(protocol=user_protocol, scheme=scheme),
        discovery=Embedded,
        topics=topics or TEST_TOPIC_SPACE,
    )
