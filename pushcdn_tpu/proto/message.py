"""Wire format: the ``Message`` model (9 reference variants + ``Migrate``)
and its canonical binary codec.

Capability parity with the reference's Cap'n Proto envelope + hand-written
enum (cdn-proto/src/message.rs:83-105 for the variants, :107-457 for
serialize/deserialize; schema in cdn-proto/schema/messages.capnp). Redesigned
TPU-first instead of using capnp:

- **Flat little-endian layout with the payload last.** The payload of the two
  hot variants (``Direct``, ``Broadcast``) is the *unprefixed tail* of the
  frame, so (a) decoding is zero-copy (a ``memoryview`` into the recv
  buffer), and (b) a frame can be dropped into a fixed-width HBM byte-tensor
  slot where ``payload_offset``/``length`` are plain int32 columns — see
  ``pushcdn_tpu.parallel.frames`` for the tensor packing.
- **One-byte kind tag** doubles as the on-device ``kind`` column.
- Sync payloads (``UserSync``/``TopicSync``) are opaque bytes whose interior
  is produced by the CRDT codec (parity with the reference nesting rkyv
  archives inside the capnp envelope, cdn-broker/src/tasks/broker/sync.rs:24-40).

Permit semantics (parity message.rs:338-341): in ``AuthenticateResponse``,
``permit == 0`` means failure (see ``context``), ``1`` means success/ack, and
``> 1`` is an actual redeemable permit.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Sequence, Tuple, Union

from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
from pushcdn_tpu.proto.error import ErrorKind, bail

BytesLike = Union[bytes, bytearray, memoryview]

# Topic is a plain small int on the wire (parity: `type Topic = u8`,
# message.rs:26). Validation/pruning lives in pushcdn_tpu.proto.topic.
Topic = int

# --- kind tags (the u8 discriminant; stable — also used on-device) ---------
KIND_AUTHENTICATE_WITH_KEY = 1
KIND_AUTHENTICATE_WITH_PERMIT = 2
KIND_AUTHENTICATE_RESPONSE = 3
KIND_DIRECT = 4
KIND_BROADCAST = 5
KIND_SUBSCRIBE = 6
KIND_UNSUBSCRIBE = 7
KIND_USER_SYNC = 8
KIND_TOPIC_SYNC = 9
KIND_MIGRATE = 10
KIND_SUBSCRIBE_FROM = 11
KIND_RETAINED = 12
KIND_LEDGER_SYNC = 13

# sequence sentinels for SubscribeFrom (durable topics, ISSUE 14): the
# top of the u64 range can never be a real retention sequence (rings
# count up from 1), so the last two values select replay modes instead
SEQ_LAST = 2**64 - 1     # replay only the last-value-cache entry
SEQ_LIVE = 2**64 - 2     # no replay: subscribe-only (wildcard patterns)

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_U16 = struct.Struct("<H")

# --- trace carrier (ISSUE 4) ----------------------------------------------
# The kind tag's high bit was reserved (kinds are 1-9): setting it means a
# fixed <u64 trace_id, u64 origin_ns> block follows the kind byte, then the
# frame continues exactly as before. Untraced frames are byte-identical to
# the pre-trace wire and pay zero decode work (hot dispatch tests exact
# kind values). Only Direct/Broadcast decode the flag here; the marshal
# auth frame carries it at the frame level (proto.trace.stamp/strip_frame).
TRACE_FLAG = 0x80
# the single source of truth for the block layout — proto.trace imports it
TRACE_BLOCK = struct.Struct("<QQ")
_TRACE_BLOCK = TRACE_BLOCK
_TRACED_HOT = frozenset((KIND_DIRECT | TRACE_FLAG, KIND_BROADCAST | TRACE_FLAG))

# --- view tag (ISSUE 11) ---------------------------------------------------
# Consensus-shaped workloads tag traced frames with the u32 view number so
# trace_report can aggregate per view. Same reserved-bit scheme as the
# trace flag itself: origin_ns is wall-clock nanoseconds, which stays below
# 2**63 until the year 2262, so its high bit was always zero on the wire.
# Setting it means a u32 view tag follows the 16-byte trace block. Frames
# without a view (and all untraced frames) are byte-identical to the PR 4
# wire — zero cost unless a view is actually carried.
TRACE_VIEW_FLAG = 1 << 63
TRACE_BLOCK_VIEW = struct.Struct("<QQI")


def pack_trace(trace) -> bytes:
    """Encode a trace context — ``(trace_id, origin_ns)`` or
    ``(trace_id, origin_ns, view)`` — into its wire block (16 or 20 B)."""
    if len(trace) > 2 and trace[2] is not None:
        return TRACE_BLOCK_VIEW.pack(trace[0], trace[1] | TRACE_VIEW_FLAG,
                                     trace[2] & 0xFFFFFFFF)
    return TRACE_BLOCK.pack(trace[0], trace[1])


def unpack_trace(view: BytesLike, off: int) -> Tuple[tuple, int]:
    """Decode the trace block at ``off``; returns ``(trace, end_offset)``
    where ``trace`` is a 2- or 3-tuple mirroring :func:`pack_trace`.
    Raises ``struct.error`` on truncation (callers wrap it in the usual
    ``Error(DESERIALIZE)``)."""
    tid, origin = TRACE_BLOCK.unpack_from(view, off)
    if origin & TRACE_VIEW_FLAG:
        (v,) = _U32.unpack_from(view, off + TRACE_BLOCK.size)
        return (tid, origin & ~TRACE_VIEW_FLAG, v), off + TRACE_BLOCK_VIEW.size
    return (tid, origin), off + TRACE_BLOCK.size


@dataclass(frozen=True, slots=True)
class AuthenticateWithKey:
    """User → marshal: prove key ownership by signing a unix timestamp.

    Parity: message.rs AuthenticateWithKey {public_key, timestamp, signature};
    flow in cdn-proto/src/connection/auth/user.rs:50-86.
    """

    public_key: bytes
    timestamp: int  # unix seconds, checked ±5 s by the marshal
    signature: bytes

    kind = KIND_AUTHENTICATE_WITH_KEY


@dataclass(frozen=True, slots=True)
class AuthenticateWithPermit:
    """User → broker: redeem the marshal-issued permit (message.rs)."""

    permit: int

    kind = KIND_AUTHENTICATE_WITH_PERMIT


@dataclass(frozen=True, slots=True)
class AuthenticateResponse:
    """Marshal/broker → user: permit semantics 0=fail, 1=ack, >1=permit.

    ``context`` is the broker endpoint on marshal success, or the failure
    reason (parity message.rs:338-341 and auth/marshal.rs:138-144).
    """

    permit: int
    context: str = ""

    kind = KIND_AUTHENTICATE_RESPONSE


class Direct:
    """Point-to-point message to ``recipient`` (a serialized public key).

    Hot-path variant: ``message`` is the unprefixed frame tail (zero-copy).
    Treat as immutable. Plain ``__slots__`` class, not a frozen dataclass:
    these two are constructed once per received message, and the frozen
    ``object.__setattr__`` ceremony was a top-3 line in the fan-out drain
    profile. Parity: message.rs Direct {recipient, message}.
    """

    __slots__ = ("recipient", "message")

    kind = KIND_DIRECT
    # lifecycle-trace context; None on the untraced hot path (class
    # attribute, so plain Directs pay nothing — see TracedDirect)
    trace = None

    def __init__(self, recipient: bytes, message: BytesLike):
        self.recipient = recipient
        self.message = message

    def __eq__(self, other):
        return (type(other) is Direct and self.recipient == other.recipient
                and self.message == other.message)

    def __hash__(self):
        return hash((KIND_DIRECT, self.recipient, self.message))

    def __repr__(self):
        return f"Direct(recipient={self.recipient!r}, <{len(self.message)} B>)"


class Broadcast:
    """Publish to every subscriber of ``topics``.

    Hot-path variant: ``message`` is the unprefixed frame tail (zero-copy).
    Treat as immutable (see :class:`Direct` on why not a dataclass).
    Parity: message.rs Broadcast {topics, message}.
    """

    __slots__ = ("topics", "message")

    kind = KIND_BROADCAST
    trace = None  # see Direct.trace

    def __init__(self, topics: Sequence[Topic], message: BytesLike):
        self.topics = topics if type(topics) is tuple else tuple(topics)
        self.message = message

    def __eq__(self, other):
        return (type(other) is Broadcast and self.topics == other.topics
                and self.message == other.message)

    def __hash__(self):
        return hash((KIND_BROADCAST, self.topics, self.message))

    def __repr__(self):
        return f"Broadcast(topics={self.topics!r}, <{len(self.message)} B>)"


class TracedDirect(Direct):
    """A :class:`Direct` carrying a lifecycle-trace context
    ``(trace_id, origin_ns)``. Same ``kind``; ``isinstance(m, Direct)``
    still matches, so routing treats it as a plain Direct — span-emission
    sites branch on ``m.trace is not None``."""

    __slots__ = ("trace",)

    def __init__(self, recipient: bytes, message: BytesLike, trace):
        self.recipient = recipient
        self.message = message
        self.trace = trace

    def __eq__(self, other):
        return (isinstance(other, Direct)
                and self.recipient == other.recipient
                and self.message == other.message)

    __hash__ = Direct.__hash__

    def __repr__(self):
        return (f"TracedDirect(recipient={self.recipient!r}, "
                f"<{len(self.message)} B>, trace={self.trace!r})")


class TracedBroadcast(Broadcast):
    """A :class:`Broadcast` carrying a lifecycle-trace context (see
    :class:`TracedDirect`)."""

    __slots__ = ("trace",)

    def __init__(self, topics: Sequence[Topic], message: BytesLike, trace):
        self.topics = topics if type(topics) is tuple else tuple(topics)
        self.message = message
        self.trace = trace

    def __eq__(self, other):
        return (isinstance(other, Broadcast) and self.topics == other.topics
                and self.message == other.message)

    __hash__ = Broadcast.__hash__

    def __repr__(self):
        return (f"TracedBroadcast(topics={self.topics!r}, "
                f"<{len(self.message)} B>, trace={self.trace!r})")


@dataclass(frozen=True, slots=True)
class Subscribe:
    """User → broker: add topic subscriptions (message.rs Subscribe)."""

    topics: Tuple[Topic, ...]

    kind = KIND_SUBSCRIBE

    def __init__(self, topics: Sequence[Topic]):
        object.__setattr__(self, "topics", tuple(topics))


@dataclass(frozen=True, slots=True)
class Unsubscribe:
    """User → broker: drop topic subscriptions (message.rs Unsubscribe)."""

    topics: Tuple[Topic, ...]

    kind = KIND_UNSUBSCRIBE

    def __init__(self, topics: Sequence[Topic]):
        object.__setattr__(self, "topics", tuple(topics))


@dataclass(frozen=True, slots=True)
class UserSync:
    """Broker ↔ broker: opaque CRDT delta of the user→broker DirectMap.

    Parity: message.rs UserSync(Vec<u8>); interior produced by
    pushcdn_tpu.broker.versioned_map serialization.
    """

    payload: BytesLike

    kind = KIND_USER_SYNC


@dataclass(frozen=True, slots=True)
class TopicSync:
    """Broker ↔ broker: opaque CRDT delta of topic subscriptions."""

    payload: BytesLike

    kind = KIND_TOPIC_SYNC


@dataclass(frozen=True, slots=True)
class LedgerSync:
    """Broker ↔ broker: opaque JSON balance sheet of the sender's
    frame-fate conservation ledger (ISSUE 20) — monotone per-link
    sent/received counters exchanged over the existing sync task so
    each hop can compute its deficit against its upstream with no
    per-frame wire overhead. Interior produced by
    ``proto.ledger.Ledger.sheet``; a receiver that cannot parse it
    ignores it (last-writer-wins per peer, no CRDT merge needed —
    counters are monotone snapshots)."""

    payload: BytesLike

    kind = KIND_LEDGER_SYNC


@dataclass(frozen=True, slots=True)
class Migrate:
    """Broker → user: re-home to ``target`` (ISSUE 12 elastic membership).

    A draining broker sends this on the ordered egress path — after every
    message already queued for the user — with a permit it pre-issued for
    the target broker, so the client dials the new home directly without a
    per-connection marshal round-trip (the batched-handoff lesson from the
    DMA streaming / "RPC Considered Harmful" lineage). ``permit == 0``
    means no pre-issued permit: the client falls back to the marshal
    re-dance. Backward compatible: kind 10 was unused, and peers that
    don't know it reject it through the existing unexpected-kind policy.
    """

    target: str  # the new home's public advertise endpoint
    permit: int = 0

    kind = KIND_MIGRATE


@dataclass(frozen=True, slots=True)
class SubscribeFrom:
    """User → broker: subscribe to a durable ``topic`` AND replay its
    retention ring from sequence ``seq`` (ISSUE 14 durable topics).

    ``seq`` addresses the broker-local per-topic sequence stream stamped
    at ingress: entries with ``entry.seq >= seq`` are replayed as
    :class:`Retained` frames on the ordered egress path, then live
    delivery splices in with no gap and no duplicate (the broker
    registers the subscription and snapshots the ring in one synchronous
    step). Sentinels: :data:`SEQ_LAST` replays only the last-value-cache
    entry; :data:`SEQ_LIVE` skips replay entirely (used with
    ``pattern``). A non-empty ``pattern`` is a hierarchical wildcard
    (``consensus.view.*``) compiled broker-side onto the interest mask;
    ``topic`` is ignored then. Backward compatible: kind 11 was unused —
    old peers fall through cold-kind decode to the documented
    unexpected-kind disconnect, exactly like PR 12's ``Migrate``.
    """

    topic: int
    seq: int = 0
    pattern: str = ""

    kind = KIND_SUBSCRIBE_FROM


@dataclass(frozen=True, slots=True)
class Retained:
    """Broker → user: one replayed retention entry — ``payload`` is the
    original broadcast body, ``seq`` its broker-local position in
    ``topic``'s sequence stream. Payload-last layout, so decode is
    zero-copy like Direct/Broadcast."""

    topic: int
    seq: int
    payload: BytesLike

    kind = KIND_RETAINED


Message = Union[
    AuthenticateWithKey,
    AuthenticateWithPermit,
    AuthenticateResponse,
    Direct,
    Broadcast,
    Subscribe,
    Unsubscribe,
    UserSync,
    TopicSync,
    LedgerSync,
    Migrate,
    SubscribeFrom,
    Retained,
]

_ALL_KINDS = {
    KIND_AUTHENTICATE_WITH_KEY,
    KIND_AUTHENTICATE_WITH_PERMIT,
    KIND_AUTHENTICATE_RESPONSE,
    KIND_DIRECT,
    KIND_BROADCAST,
    KIND_SUBSCRIBE,
    KIND_UNSUBSCRIBE,
    KIND_USER_SYNC,
    KIND_TOPIC_SYNC,
    KIND_MIGRATE,
    KIND_SUBSCRIBE_FROM,
    KIND_RETAINED,
    KIND_LEDGER_SYNC,
}


def serialize(msg: Message) -> bytes:
    """Encode ``msg`` into one frame (without the outer u32 length prefix —
    that belongs to the transport's length-delimited framing, parity
    protocols/mod.rs:353-394).

    Raises ``Error(SERIALIZE)`` on out-of-range fields and
    ``Error(EXCEEDED_SIZE)`` if the frame would exceed ``MAX_MESSAGE_SIZE``.
    """
    kind = msg.kind
    try:
        if kind == KIND_DIRECT:
            recipient = msg.recipient
            trace = msg.trace
            if trace is None:
                frame = b"".join((b"\x04", _U32.pack(len(recipient)),
                                  recipient, msg.message))
            else:
                frame = b"".join((b"\x84", pack_trace(trace),
                                  _U32.pack(len(recipient)), recipient,
                                  msg.message))
        elif kind == KIND_BROADCAST:
            topics = msg.topics
            trace = msg.trace
            if trace is None:
                frame = b"".join((b"\x05", _U16.pack(len(topics)),
                                  bytes(topics), msg.message))
            else:
                frame = b"".join((b"\x85", pack_trace(trace),
                                  _U16.pack(len(topics)), bytes(topics),
                                  msg.message))
        elif kind in (KIND_SUBSCRIBE, KIND_UNSUBSCRIBE):
            topics = msg.topics
            out = bytearray(1 + 2 + len(topics))
            out[0] = kind
            _U16.pack_into(out, 1, len(topics))
            out[3:] = bytes(topics)
            frame = bytes(out)
        elif kind in (KIND_USER_SYNC, KIND_TOPIC_SYNC, KIND_LEDGER_SYNC):
            frame = bytes([kind]) + bytes(msg.payload)
        elif kind == KIND_AUTHENTICATE_WITH_KEY:
            pk, sig = msg.public_key, msg.signature
            frame = (
                bytes([kind])
                + _U32.pack(len(pk)) + pk
                + _U64.pack(msg.timestamp)
                + _U32.pack(len(sig)) + sig
            )
        elif kind == KIND_AUTHENTICATE_WITH_PERMIT:
            frame = bytes([kind]) + _U64.pack(msg.permit)
        elif kind == KIND_AUTHENTICATE_RESPONSE:
            ctx = msg.context.encode("utf-8")
            frame = bytes([kind]) + _U64.pack(msg.permit) + _U32.pack(len(ctx)) + ctx
        elif kind == KIND_MIGRATE:
            tgt = msg.target.encode("utf-8")
            frame = bytes([kind]) + _U64.pack(msg.permit) + _U32.pack(len(tgt)) + tgt
        elif kind == KIND_SUBSCRIBE_FROM:
            pat = msg.pattern.encode("utf-8")
            frame = (bytes([kind, msg.topic]) + _U64.pack(msg.seq) + pat)
        elif kind == KIND_RETAINED:
            frame = b"".join((bytes([kind, msg.topic]),
                              _U64.pack(msg.seq), msg.payload))
        else:  # pragma: no cover - unreachable with the Message union
            bail(ErrorKind.SERIALIZE, f"unknown message kind {kind}")
    except (struct.error, ValueError) as exc:
        # ValueError covers topic values outside u8 range (bytes(topics)).
        bail(ErrorKind.SERIALIZE, f"field out of range serializing kind {kind}", exc)
    if len(frame) > MAX_MESSAGE_SIZE:
        bail(ErrorKind.EXCEEDED_SIZE,
             f"serialized frame {len(frame)} B exceeds max {MAX_MESSAGE_SIZE} B")
    return frame


def deserialize(frame: BytesLike) -> Message:
    """Decode one frame. ``Direct``/``Broadcast``/sync payloads are returned
    as zero-copy ``memoryview``s into ``frame``.

    Raises ``Error(DESERIALIZE)`` on malformed input — the broker policy for
    that is to disconnect the peer (parity tasks/user/handler.rs:106-118).
    """
    view = memoryview(frame)
    n = len(view)
    if n < 1:
        bail(ErrorKind.DESERIALIZE, "empty frame")
    if n > MAX_MESSAGE_SIZE:
        bail(ErrorKind.EXCEEDED_SIZE, f"frame {n} B exceeds max {MAX_MESSAGE_SIZE} B")
    kind = view[0]
    try:
        if kind == KIND_DIRECT:
            (rlen,) = _U32.unpack_from(view, 1)
            if 5 + rlen > n:
                bail(ErrorKind.DESERIALIZE, "Direct recipient overruns frame")
            return Direct(recipient=bytes(view[5:5 + rlen]), message=view[5 + rlen:])
        if kind == KIND_BROADCAST:
            (ntopics,) = _U16.unpack_from(view, 1)
            if 3 + ntopics > n:
                bail(ErrorKind.DESERIALIZE, "Broadcast topics overrun frame")
            topics = tuple(view[3:3 + ntopics])
            return Broadcast(topics=topics, message=view[3 + ntopics:])
        if kind in (KIND_SUBSCRIBE, KIND_UNSUBSCRIBE):
            (ntopics,) = _U16.unpack_from(view, 1)
            if 3 + ntopics != n:
                bail(ErrorKind.DESERIALIZE, "Subscribe/Unsubscribe length mismatch")
            topics = tuple(view[3:3 + ntopics])
            return Subscribe(topics) if kind == KIND_SUBSCRIBE else Unsubscribe(topics)
        if kind == KIND_USER_SYNC:
            return UserSync(payload=view[1:])
        if kind == KIND_TOPIC_SYNC:
            return TopicSync(payload=view[1:])
        if kind == KIND_LEDGER_SYNC:
            return LedgerSync(payload=view[1:])
        if kind == KIND_AUTHENTICATE_WITH_KEY:
            off = 1
            (pklen,) = _U32.unpack_from(view, off)
            off += 4
            pk = bytes(view[off:off + pklen])
            if len(pk) != pklen:
                bail(ErrorKind.DESERIALIZE, "AuthenticateWithKey pubkey overruns frame")
            off += pklen
            (ts,) = _U64.unpack_from(view, off)
            off += 8
            (siglen,) = _U32.unpack_from(view, off)
            off += 4
            sig = bytes(view[off:off + siglen])
            if len(sig) != siglen or off + siglen != n:
                bail(ErrorKind.DESERIALIZE, "AuthenticateWithKey signature length mismatch")
            return AuthenticateWithKey(public_key=pk, timestamp=ts, signature=sig)
        if kind == KIND_AUTHENTICATE_WITH_PERMIT:
            if n != 9:
                bail(ErrorKind.DESERIALIZE, "AuthenticateWithPermit length mismatch")
            (permit,) = _U64.unpack_from(view, 1)
            return AuthenticateWithPermit(permit=permit)
        if kind == KIND_AUTHENTICATE_RESPONSE:
            (permit,) = _U64.unpack_from(view, 1)
            (ctxlen,) = _U32.unpack_from(view, 9)
            ctx = bytes(view[13:13 + ctxlen])
            if len(ctx) != ctxlen or 13 + ctxlen != n:
                bail(ErrorKind.DESERIALIZE, "AuthenticateResponse context length mismatch")
            try:
                context = ctx.decode("utf-8")
            except UnicodeDecodeError as exc:
                # a hostile peer's bytes must surface as the documented
                # Error(DESERIALIZE), never a loose UnicodeDecodeError
                bail(ErrorKind.DESERIALIZE,
                     "AuthenticateResponse context is not UTF-8", exc)
            return AuthenticateResponse(permit=permit, context=context)
        if kind == KIND_MIGRATE:
            (permit,) = _U64.unpack_from(view, 1)
            (tlen,) = _U32.unpack_from(view, 9)
            tgt = bytes(view[13:13 + tlen])
            if len(tgt) != tlen or 13 + tlen != n:
                bail(ErrorKind.DESERIALIZE, "Migrate target length mismatch")
            try:
                target = tgt.decode("utf-8")
            except UnicodeDecodeError as exc:
                bail(ErrorKind.DESERIALIZE, "Migrate target is not UTF-8", exc)
            return Migrate(target=target, permit=permit)
        if kind == KIND_SUBSCRIBE_FROM:
            if n < 10:
                bail(ErrorKind.DESERIALIZE, "SubscribeFrom truncated")
            (seq,) = _U64.unpack_from(view, 2)
            try:
                pattern = bytes(view[10:]).decode("utf-8")
            except UnicodeDecodeError as exc:
                bail(ErrorKind.DESERIALIZE,
                     "SubscribeFrom pattern is not UTF-8", exc)
            return SubscribeFrom(topic=view[1], seq=seq, pattern=pattern)
        if kind == KIND_RETAINED:
            if n < 10:
                bail(ErrorKind.DESERIALIZE, "Retained truncated")
            (seq,) = _U64.unpack_from(view, 2)
            return Retained(topic=view[1], seq=seq, payload=view[10:])
        if kind in _TRACED_HOT:
            # traced hot frame: 16- or 20-byte trace block (view-tagged)
            # after the kind byte, then the ordinary layout (rare by
            # construction: 1/1024 default sampling)
            if n < 1 + _TRACE_BLOCK.size:
                bail(ErrorKind.DESERIALIZE, "truncated trace block")
            trace, off = unpack_trace(view, 1)
            if kind & ~TRACE_FLAG == KIND_DIRECT:
                (rlen,) = _U32.unpack_from(view, off)
                p = off + 4 + rlen
                if p > n:
                    bail(ErrorKind.DESERIALIZE,
                         "Direct recipient overruns frame")
                return TracedDirect(bytes(view[off + 4:p]), view[p:], trace)
            (ntopics,) = _U16.unpack_from(view, off)
            p = off + 2 + ntopics
            if p > n:
                bail(ErrorKind.DESERIALIZE, "Broadcast topics overrun frame")
            return TracedBroadcast(tuple(view[off + 2:p]), view[p:], trace)
    except struct.error as exc:
        bail(ErrorKind.DESERIALIZE, f"truncated frame for kind {kind}", exc)
    bail(ErrorKind.DESERIALIZE, f"unknown message kind {kind}")


def materialize(msg: Message) -> Message:
    """Copy any zero-copy ``memoryview`` fields into owned ``bytes``.

    ``deserialize`` returns views into the receive buffer; the buffer's pool
    permit cannot be released while views outlive it. Convenience APIs
    (``Connection.recv_message``) materialize so the permit accounting stays
    exact; the broker hot path uses ``recv_raw`` + ``deserialize`` and
    releases the permit after fan-out instead.
    """
    kind = msg.kind
    if kind == KIND_DIRECT and isinstance(msg.message, memoryview):
        if msg.trace is not None:
            return TracedDirect(msg.recipient, bytes(msg.message), msg.trace)
        return Direct(recipient=msg.recipient, message=bytes(msg.message))
    if kind == KIND_BROADCAST and isinstance(msg.message, memoryview):
        if msg.trace is not None:
            return TracedBroadcast(msg.topics, bytes(msg.message), msg.trace)
        return Broadcast(topics=msg.topics, message=bytes(msg.message))
    if kind in (KIND_USER_SYNC, KIND_TOPIC_SYNC, KIND_LEDGER_SYNC) \
            and isinstance(msg.payload, memoryview):
        cls = (UserSync if kind == KIND_USER_SYNC
               else TopicSync if kind == KIND_TOPIC_SYNC else LedgerSync)
        return cls(payload=bytes(msg.payload))
    if kind == KIND_RETAINED and isinstance(msg.payload, memoryview):
        return Retained(topic=msg.topic, seq=msg.seq, payload=bytes(msg.payload))
    return msg


def deserialize_owned(frame: BytesLike) -> Message:
    """``materialize(deserialize(frame))`` fused for the hot variants: when
    ``frame`` is immutable ``bytes`` (the reader's complete-frame payloads
    are), slicing it copies directly — one object construction and one copy
    instead of view + materialize + recopy. Convenience receive APIs use
    this; semantics are identical to the two-step path."""
    t = type(frame)
    if t is bytes or t is memoryview:
        n = len(frame)
        if 1 <= n <= MAX_MESSAGE_SIZE:
            kind = frame[0]
            try:
                if kind == KIND_DIRECT:
                    (rlen,) = _U32.unpack_from(frame, 1)
                    if 5 + rlen <= n:
                        if t is memoryview:  # chunk views: copy out here
                            return Direct(
                                recipient=bytes(frame[5:5 + rlen]),
                                message=bytes(frame[5 + rlen:]))
                        return Direct(recipient=frame[5:5 + rlen],
                                      message=frame[5 + rlen:])
                    bail(ErrorKind.DESERIALIZE,
                         "Direct recipient overruns frame")
                if kind == KIND_BROADCAST:
                    (ntopics,) = _U16.unpack_from(frame, 1)
                    if 3 + ntopics <= n:
                        if t is memoryview:
                            return Broadcast(
                                topics=tuple(frame[3:3 + ntopics]),
                                message=bytes(frame[3 + ntopics:]))
                        return Broadcast(topics=tuple(frame[3:3 + ntopics]),
                                         message=frame[3 + ntopics:])
                    bail(ErrorKind.DESERIALIZE,
                         "Broadcast topics overrun frame")
            except struct.error as exc:
                # a 1-4 byte truncated frame must surface the same
                # Error(DESERIALIZE) the two-step path raises — callers'
                # malformed-frame disconnect policy catches Error only
                bail(ErrorKind.DESERIALIZE,
                     f"truncated frame for kind {kind}", exc)
    return materialize(deserialize(bytes(frame) if t is memoryview
                                   else frame))


_native_decode = None
_native_decode_tried = False


# Zero-copy decode threshold: payloads at or above this come back as
# memoryviews over the chunk buffer; smaller ones are owned copies. Two
# reasons, both measured: (a) below a few hundred bytes the memcpy is
# cheaper than constructing the slice view, so tiny payloads gain nothing
# from views; (b) a retained view pins its WHOLE read chunk (up to
# Connection._READ_CHUNK) after the pool permit returns — copying small
# payloads caps that invisible amplification at chunk_size/threshold per
# retained message instead of chunk_size/payload (an app retaining 10K
# tiny messages would otherwise pin gigabytes the pool can't see).
ZERO_COPY_MIN = 256


def decode_frames(buf: bytes, offs, lens, start: int = 0,
                  zero_copy: bool = False) -> list:
    """Decode a parse batch's frames straight off the shared chunk buffer
    (transport ``FrameChunk``) — the fan-out drain's hot loop. Inline
    little-endian field reads replace per-frame memoryview + Struct calls;
    payload/recipient slices of the ``bytes`` buffer are the single owned
    copy. Cold kinds and malformed frames take the general path (which
    raises the usual ``Error(DESERIALIZE)``).

    ``zero_copy=True`` skips even that one payload copy for payloads of
    at least ``ZERO_COPY_MIN`` bytes: Broadcast/Direct ``message`` fields
    come back as memoryviews over ``buf`` (the views' reference chain
    keeps the buffer alive after the chunk's pool permit is released —
    one retained message can pin at most one read chunk, and the
    threshold caps the pin-per-retained-byte amplification; see
    ``ZERO_COPY_MIN``). Smaller payloads are owned copies either way
    (cheaper than the view object). Direct ``recipient`` stays an owned
    copy: it is small and consumed as a dict key.

    The loop itself runs in C when the native library is available
    (native/pydecode.cpp — same construction, same fallback semantics,
    ~5x less per-message cost); this Python body is the fallback and the
    executable specification."""
    global _native_decode, _native_decode_tried
    if not _native_decode_tried:
        from pushcdn_tpu import native as _native_mod
        _native_decode = _native_mod.pydecode()
        _native_decode_tried = True
    zc_min = ZERO_COPY_MIN if zero_copy else 0
    if _native_decode is not None:
        res = _native_decode(buf, offs, lens, start,
                             Broadcast, Direct, deserialize_owned,
                             zc_min)
        if res is not None:
            return res
    out = []
    append = out.append
    mv = memoryview(buf) if zero_copy else None
    for i in range(start, len(offs)):
        o = offs[i]
        n = lens[i]
        if n >= 3:
            kind = buf[o]
            if kind == KIND_BROADCAST:
                nt = buf[o + 1] | (buf[o + 2] << 8)
                p = o + 3 + nt
                if p <= o + n:
                    body = mv[p:o + n] if zero_copy \
                        and o + n - p >= zc_min else buf[p:o + n]
                    append(Broadcast(tuple(buf[o + 3:p]), body))
                    continue
            elif kind == KIND_DIRECT and n >= 5:
                rlen = (buf[o + 1] | (buf[o + 2] << 8)
                        | (buf[o + 3] << 16) | (buf[o + 4] << 24))
                p = o + 5 + rlen
                if p <= o + n:
                    body = mv[p:o + n] if zero_copy \
                        and o + n - p >= zc_min else buf[p:o + n]
                    append(Direct(bytes(buf[o + 5:p]), body))
                    continue
        append(deserialize_owned(bytes(buf[o:o + n])))
    return out


def with_trace(msg: Message, trace) -> Message:
    """The traced twin of a hot message (Direct/Broadcast); other kinds
    are returned unchanged (their frames carry traces at the frame level
    only — see proto.trace.stamp_frame)."""
    kind = msg.kind
    if kind == KIND_DIRECT:
        return TracedDirect(msg.recipient, msg.message, trace)
    if kind == KIND_BROADCAST:
        return TracedBroadcast(msg.topics, msg.message, trace)
    return msg


def peek_kind(frame: BytesLike) -> int:
    """Read the kind tag without decoding — lets hot loops dispatch before
    (or instead of) a full deserialize."""
    if len(frame) < 1:
        bail(ErrorKind.DESERIALIZE, "empty frame")
    return memoryview(frame)[0]
