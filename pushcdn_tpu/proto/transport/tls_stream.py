"""TLS 1.3 over any :class:`RawStream` via ``ssl.MemoryBIO``.

Real QUIC runs the TLS 1.3 handshake over its reliable crypto streams
(RFC 9001); the QUIC-class transport mirrors that: the userspace ARQ
provides the reliable ordered byte stream, and this wrapper runs the
actual TLS state machine on top, reusing the same CA/leaf plumbing as
the TcpTls edge (parity with the reference's quinn configuration,
cdn-proto/src/connection/protocols/quic.rs:37-146, where rustls secures
the stream against the pinned CA).

The wrapper is transport-generic: anything exposing ``RawStream``
(read_some/write/close/abort) can be secured with it.
"""

from __future__ import annotations

import asyncio
import ssl
from typing import Optional

from pushcdn_tpu.proto.transport.base import RawStream

_CHUNK = 256 * 1024


class TlsStream(RawStream):
    """A ``RawStream`` carrying TLS records over an inner ``RawStream``."""

    def __init__(self, inner: RawStream, ssl_object: ssl.SSLObject,
                 incoming: ssl.MemoryBIO, outgoing: ssl.MemoryBIO):
        self._inner = inner
        self._obj = ssl_object
        self._incoming = incoming
        self._outgoing = outgoing
        # Serializes ciphertext egress: the reader task can emit records
        # too (KeyUpdate replies), and an inner.write blocked on transport
        # backpressure must not interleave with another task's bytes
        # mid-record.
        self._pump_lock = asyncio.Lock()

    # -- construction --------------------------------------------------------

    @classmethod
    async def wrap_client(cls, inner: RawStream, context: ssl.SSLContext,
                          server_hostname: str) -> "TlsStream":
        incoming, outgoing = ssl.MemoryBIO(), ssl.MemoryBIO()
        obj = context.wrap_bio(incoming, outgoing, server_side=False,
                               server_hostname=server_hostname)
        self = cls(inner, obj, incoming, outgoing)
        await self._handshake()
        return self

    @classmethod
    async def wrap_server(cls, inner: RawStream,
                          context: ssl.SSLContext) -> "TlsStream":
        incoming, outgoing = ssl.MemoryBIO(), ssl.MemoryBIO()
        obj = context.wrap_bio(incoming, outgoing, server_side=True)
        self = cls(inner, obj, incoming, outgoing)
        await self._handshake()
        return self

    async def _handshake(self) -> None:
        while True:
            try:
                self._obj.do_handshake()
                await self._pump_out()
                return
            except ssl.SSLWantReadError:
                await self._pump_out()
                chunk = await self._inner.read_some(_CHUNK)
                self._incoming.write(chunk)
            except ssl.SSLWantWriteError:  # pragma: no cover - MemoryBIO
                await self._pump_out()     # is unbounded; defensive only

    async def _pump_out(self) -> None:
        async with self._pump_lock:
            data = self._outgoing.read()
            if data:
                await self._inner.write(data)

    # -- RawStream interface -------------------------------------------------

    async def read_some(self, max_n: int) -> bytes:
        out = bytearray()
        while True:
            # drain every decrypted record available up to max_n in one
            # call — SSLObject.read is one SSL_read (<= one ~16 KiB
            # record), and returning per-record would defeat the
            # Connection reader's bulk-chunk batch parsing
            try:
                while len(out) < max_n:
                    data = self._obj.read(max_n - len(out))
                    if not data:
                        break
                    out += data
            except ssl.SSLWantReadError:
                pass
            except ssl.SSLZeroReturnError:
                # clean TLS close_notify from the peer
                if out:
                    return bytes(out)
                raise asyncio.IncompleteReadError(b"", 1)
            # OpenSSL can queue records while reading (e.g. the mandatory
            # reply to a peer KeyUpdate, RFC 8446 §4.6.3); a read-mostly
            # connection must still transmit them
            if self._outgoing.pending:
                await self._pump_out()
            if out:
                return bytes(out)
            # ARQ-level EOF propagates as IncompleteReadError from the
            # inner read — exactly what Connection's reader expects
            chunk = await self._inner.read_some(_CHUNK)
            self._incoming.write(chunk)

    async def read_exactly(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            buf += await self.read_some(n - len(buf))
        return bytes(buf)

    async def write(self, data) -> None:
        # SSLObject.write takes any buffer-protocol object and the writer
        # loop awaits this flush before reusing its buffer — no copy needed
        view = memoryview(data)
        total = len(view)
        written = 0
        while written < total:
            # SSLObject.write fragments into <=16 KiB records in the BIO;
            # bound each burst so the ciphertext pump interleaves with
            # encryption instead of buffering the whole payload
            n = self._obj.write(view[written:written + _CHUNK])
            written += n
            await self._pump_out()

    async def close(self) -> None:
        try:
            self._obj.unwrap()  # queue close_notify
        except (ssl.SSLWantReadError, ssl.SSLError, OSError):
            pass
        try:
            await self._pump_out()
        except Exception:
            pass
        await self._inner.close()

    def abort(self) -> None:
        self._inner.abort()
