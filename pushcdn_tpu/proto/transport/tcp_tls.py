"""TLS-over-TCP transport (the production user-facing edge).

Capability parity with cdn-proto/src/connection/protocols/tcp_tls.rs:44-254:
server presents a leaf cert derived from the local (or production) CA with
SAN ``pushcdn``; clients verify against that CA; no mutual TLS (user
authentication is the signed-timestamp handshake at L4, not client certs).
"""

from __future__ import annotations

import asyncio
import socket
import ssl

from pushcdn_tpu.proto.crypto.tls import (
    Certificate,
    client_context_for,
    local_certificate,
)
from pushcdn_tpu.proto.error import ErrorKind, bail, parse_endpoint
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import (
    CONNECT_TIMEOUT_S,
    AsyncioStream,
    Connection,
    Listener,
    Protocol,
    UnfinalizedConnection,
)


class _TlsUnfinalized(UnfinalizedConnection):
    def __init__(self, reader, writer):
        self._reader, self._writer = reader, writer

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        # TLS handshake already completed by asyncio's start_server(ssl=...);
        # the accept loop stays cheap because asyncio performs the handshake
        # before invoking the client callback.
        sock = self._writer.get_extra_info("socket")
        if sock is not None:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        return Connection(AsyncioStream(self._reader, self._writer), limiter,
                          label="tcp+tls")


class TcpTlsListener(Listener):
    def __init__(self):
        self._accept_q: "asyncio.Queue" = asyncio.Queue()
        self._server: asyncio.AbstractServer = None
        self._closed = False
        self.bound_port: int = 0

    async def _on_client(self, reader, writer):
        await self._accept_q.put(_TlsUnfinalized(reader, writer))

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        item = await self._accept_q.get()
        if item is None:  # close() sentinel
            bail(ErrorKind.CONNECTION, "listener closed")
        return item

    async def close(self) -> None:
        self._closed = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._accept_q.put_nowait(None)  # wake any blocked accept()


def _note_io_impl() -> None:
    """TLS always runs on the asyncio stream pair (Python's ssl module owns
    the record layer, so there is no plaintext fd for io_uring to drive).
    When the process selected the uring data plane, log the fallback ONCE
    instead of silently ignoring the knob — honest labeling over silence."""
    import os
    if os.environ.get("PUSHCDN_IO_IMPL") or os.environ.get("PUSHCDN_IO_URING"):
        from pushcdn_tpu.proto.transport import uring as uring_mod
        uring_mod.warn_tls_fallback_once()


class TcpTls(Protocol):
    name = "tcp+tls"

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        _note_io_impl()
        host, port = parse_endpoint(endpoint)
        ctx, server_hostname = client_context_for(use_local_authority, host)
        try:
            async with asyncio.timeout(CONNECT_TIMEOUT_S):
                reader, writer = await asyncio.open_connection(
                    host, port, ssl=ctx, server_hostname=server_hostname)
        except (OSError, ssl.SSLError, asyncio.TimeoutError) as exc:
            bail(ErrorKind.CONNECTION, f"tls connect to {endpoint} failed", exc)
        return Connection(AsyncioStream(reader, writer), limiter,
                          label=f"tcp+tls:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str,
                   certificate: "Certificate | None" = None,
                   reuse_port: bool = False) -> Listener:
        _note_io_impl()
        host, port = parse_endpoint(endpoint)
        if certificate is None:
            certificate = local_certificate()
        listener = TcpTlsListener()
        try:
            server = await asyncio.start_server(
                listener._on_client, host, port,
                ssl=certificate.server_context(),
                **({"reuse_port": True} if reuse_port else {}))
        except (OSError, ssl.SSLError, ValueError) as exc:
            bail(ErrorKind.CONNECTION, f"tls bind to {endpoint} failed", exc)
        listener._server = server
        listener.bound_port = server.sockets[0].getsockname()[1]
        return listener
