"""In-process "memory" transport for deterministic single-process tests.

Capability parity with cdn-proto/src/connection/protocols/memory.rs:32-204:
listeners live in a process-global registry keyed by endpoint string; a
connect hands one side of a duplex pipe to the listener's accept queue.
This is the seam that lets whole-system integration tests (marshal + brokers
+ clients) run in one process with no sockets (SURVEY.md §4).
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Dict, Optional, Tuple

from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import (
    Connection,
    Listener,
    Protocol,
    RawStream,
    UnfinalizedConnection,
)

_DUPLEX_BUFFER = 8192  # parity: 8192-byte duplex buffers (memory.rs)

# The conformance default stays at the reference's 8 KiB; deployments and
# benches that push large frames through the in-process transport can widen
# it (``Memory.set_duplex_window``) so the window constant — test-infra
# parity, not a behavioral guarantee — doesn't bound throughput.
_duplex_window = _DUPLEX_BUFFER


class _BoundedBuffer:
    """A bounded in-process byte pipe with real backpressure: writers
    block while ``size >= capacity`` (parity with the reference's 8192-byte
    duplex halves — a fast producer cannot grow memory unboundedly).

    Storage is a deque of immutable byte chunks, not a flat bytearray:
    a write appends (at most one copy, from the caller's possibly-reused
    buffer), and ``read_some`` pops a whole chunk with ZERO copies — the
    reader's whole-chunk scan path then parses frames out of that very
    object, so a frame's bytes are copied once end-to-end through the
    in-process transport instead of four times."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity if capacity is not None else _duplex_window
        self._chunks: "deque" = deque()
        self._size = 0
        self._eof = False
        self._cond = asyncio.Condition()

    async def write(self, data) -> None:
        async with self._cond:
            # Chunk so a frame larger than the capacity still flows.
            view = memoryview(data)
            n = len(view)
            off = 0
            while off < n:
                while self._size >= self.capacity and not self._eof:
                    await self._cond.wait()
                if self._eof:
                    raise ConnectionResetError("memory stream closed")
                room = max(self.capacity - self._size, 1)
                piece = bytes(view[off:off + room])  # detach: caller's
                off += len(piece)                    # buffer may be reused
                self._chunks.append(piece)
                self._size += len(piece)
                self._cond.notify_all()

    async def read_exactly(self, n: int) -> bytes:
        # Consume incrementally: n may exceed the buffer capacity (a frame
        # bigger than the duplex window streams through it).
        out = bytearray()
        async with self._cond:
            while len(out) < n:
                if not self._chunks:
                    if self._eof:
                        raise asyncio.IncompleteReadError(bytes(out), n)
                    await self._cond.wait()
                    continue
                head = self._chunks[0]
                take = n - len(out)
                if len(head) <= take:
                    self._chunks.popleft()
                    out += head
                else:
                    out += head[:take]
                    self._chunks[0] = head[take:]
                self._size -= min(take, len(head))
                self._cond.notify_all()
            return bytes(out)

    async def read_some(self, max_n: int) -> bytes:
        async with self._cond:
            while not self._chunks:
                if self._eof:
                    raise asyncio.IncompleteReadError(b"", 1)
                await self._cond.wait()
            head = self._chunks[0]
            if len(head) <= max_n:
                # whole-chunk take: zero copies
                self._chunks.popleft()
                self._size -= len(head)
            else:
                self._chunks[0] = head[max_n:]
                head = head[:max_n]
                self._size -= max_n
            self._cond.notify_all()
            return head

    def set_eof(self) -> None:
        self._eof = True
        # May be called from sync context (abort); schedule the wakeup.
        async def _notify():
            async with self._cond:
                self._cond.notify_all()
        try:
            asyncio.get_running_loop().create_task(_notify())
        except RuntimeError:
            pass


class _PipeStream(RawStream):
    """One side of an in-process duplex over two bounded buffers."""

    def __init__(self, rx: _BoundedBuffer, tx: _BoundedBuffer):
        self._rx = rx
        self._tx = tx
        self._closed = False

    async def read_exactly(self, n: int) -> bytes:
        return await self._rx.read_exactly(n)

    async def read_some(self, max_n: int) -> bytes:
        return await self._rx.read_some(max_n)

    async def write(self, data) -> None:
        if self._closed:
            raise ConnectionResetError("memory stream closed")
        await self._tx.write(data)  # the buffer detaches per chunk itself

    async def close(self) -> None:
        self.abort()

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            self._tx.set_eof()
            self._rx.set_eof()


def _duplex() -> Tuple[_PipeStream, _PipeStream]:
    ab, ba = _BoundedBuffer(), _BoundedBuffer()
    return _PipeStream(rx=ba, tx=ab), _PipeStream(rx=ab, tx=ba)


class _Registry:
    """Process-global endpoint → listener map (parity: the reference's
    ``OnceLock<RwLock<HashMap<String, ChannelExchange>>>``, memory.rs:32-36)."""

    def __init__(self):
        self.listeners: Dict[str, "MemoryListener"] = {}


_REGISTRY = _Registry()


class _MemoryUnfinalized(UnfinalizedConnection):
    def __init__(self, stream: _PipeStream):
        self._stream = stream

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        return Connection(self._stream, limiter, label="memory")


class MemoryListener(Listener):
    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._accept_q: "asyncio.Queue[_PipeStream]" = asyncio.Queue()
        self._closed = False

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        stream = await self._accept_q.get()
        return _MemoryUnfinalized(stream)

    async def close(self) -> None:
        self._closed = True
        _REGISTRY.listeners.pop(self.endpoint, None)


class Memory(Protocol):
    """The in-process transport (parity protocols/memory.rs)."""

    name = "memory"

    @staticmethod
    def set_duplex_window(capacity: int) -> int:
        """Set the duplex-buffer capacity used by subsequently-created
        connections; returns the previous value. 8192 (the reference
        constant) is the default."""
        global _duplex_window
        prev = _duplex_window
        _duplex_window = capacity
        return prev

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        listener = _REGISTRY.listeners.get(endpoint)
        if listener is None or listener._closed:
            bail(ErrorKind.CONNECTION, f"no memory listener bound at {endpoint!r}")
        ours, theirs = _duplex()
        await listener._accept_q.put(theirs)
        return Connection(ours, limiter, label=f"memory:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str, certificate=None,
                   reuse_port: bool = False) -> Listener:
        if reuse_port:
            bail(ErrorKind.CONNECTION,
                 "memory transport has no kernel socket to SO_REUSEPORT")
        if endpoint in _REGISTRY.listeners:
            bail(ErrorKind.CONNECTION, f"memory endpoint {endpoint!r} already bound")
        listener = MemoryListener(endpoint)
        _REGISTRY.listeners[endpoint] = listener
        return listener


async def gen_testing_connection_pair(limiter: Limiter = NO_LIMIT
                                      ) -> Tuple[Connection, Connection]:
    """Directly build a connected pair (parity ``gen_testing_connection``,
    memory.rs — used heavily by the broker injection harness)."""
    a, b = _duplex()
    return Connection(a, limiter, "memory:a"), Connection(b, limiter, "memory:b")
