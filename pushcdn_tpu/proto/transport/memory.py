"""In-process "memory" transport for deterministic single-process tests.

Capability parity with cdn-proto/src/connection/protocols/memory.rs:32-204:
listeners live in a process-global registry keyed by endpoint string; a
connect hands one side of a duplex pipe to the listener's accept queue.
This is the seam that lets whole-system integration tests (marshal + brokers
+ clients) run in one process with no sockets (SURVEY.md §4).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Tuple

from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import (
    Connection,
    Listener,
    Protocol,
    RawStream,
    UnfinalizedConnection,
)

_DUPLEX_BUFFER = 8192  # parity: 8192-byte duplex buffers (memory.rs)


class _PipeStream(RawStream):
    """One side of an in-process duplex: reads from its own StreamReader,
    writes by feeding the peer's StreamReader."""

    def __init__(self):
        self.reader = asyncio.StreamReader(limit=2**26)
        self.peer: "_PipeStream" = None  # set by _duplex()
        self._closed = False

    async def read_exactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def write(self, data) -> None:
        if self._closed or self.peer is None:
            raise ConnectionResetError("memory stream closed")
        if self.peer._closed:
            raise ConnectionResetError("peer closed")
        self.peer.reader.feed_data(bytes(data))
        # Cooperative backpressure: yield so the peer can drain.
        if len(self.peer.reader._buffer) > _DUPLEX_BUFFER:  # noqa: SLF001
            await asyncio.sleep(0)

    async def close(self) -> None:
        self.abort()

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            if self.peer is not None:
                try:
                    self.peer.reader.feed_eof()
                except Exception:
                    pass
            try:
                self.reader.feed_eof()
            except Exception:
                pass


def _duplex() -> Tuple[_PipeStream, _PipeStream]:
    a, b = _PipeStream(), _PipeStream()
    a.peer, b.peer = b, a
    return a, b


class _Registry:
    """Process-global endpoint → listener map (parity: the reference's
    ``OnceLock<RwLock<HashMap<String, ChannelExchange>>>``, memory.rs:32-36)."""

    def __init__(self):
        self.listeners: Dict[str, "MemoryListener"] = {}


_REGISTRY = _Registry()


class _MemoryUnfinalized(UnfinalizedConnection):
    def __init__(self, stream: _PipeStream):
        self._stream = stream

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        return Connection(self._stream, limiter, label="memory")


class MemoryListener(Listener):
    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._accept_q: "asyncio.Queue[_PipeStream]" = asyncio.Queue()
        self._closed = False

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        stream = await self._accept_q.get()
        return _MemoryUnfinalized(stream)

    async def close(self) -> None:
        self._closed = True
        _REGISTRY.listeners.pop(self.endpoint, None)


class Memory(Protocol):
    """The in-process transport (parity protocols/memory.rs)."""

    name = "memory"

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        listener = _REGISTRY.listeners.get(endpoint)
        if listener is None or listener._closed:
            bail(ErrorKind.CONNECTION, f"no memory listener bound at {endpoint!r}")
        ours, theirs = _duplex()
        await listener._accept_q.put(theirs)
        return Connection(ours, limiter, label=f"memory:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str, certificate=None) -> Listener:
        if endpoint in _REGISTRY.listeners:
            bail(ErrorKind.CONNECTION, f"memory endpoint {endpoint!r} already bound")
        listener = MemoryListener(endpoint)
        _REGISTRY.listeners[endpoint] = listener
        return listener


async def gen_testing_connection_pair(limiter: Limiter = NO_LIMIT
                                      ) -> Tuple[Connection, Connection]:
    """Directly build a connected pair (parity ``gen_testing_connection``,
    memory.rs — used heavily by the broker injection harness)."""
    a, b = _duplex()
    return Connection(a, limiter, "memory:a"), Connection(b, limiter, "memory:b")
