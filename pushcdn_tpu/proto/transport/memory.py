"""In-process "memory" transport for deterministic single-process tests.

Capability parity with cdn-proto/src/connection/protocols/memory.rs:32-204:
listeners live in a process-global registry keyed by endpoint string; a
connect hands one side of a duplex pipe to the listener's accept queue.
This is the seam that lets whole-system integration tests (marshal + brokers
+ clients) run in one process with no sockets (SURVEY.md §4).
"""

from __future__ import annotations

import asyncio
import itertools
import random
from collections import deque
from typing import Dict, Optional, Tuple

from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import (
    Connection,
    Listener,
    Protocol,
    RawStream,
    UnfinalizedConnection,
)

_DUPLEX_BUFFER = 8192  # parity: 8192-byte duplex buffers (memory.rs)

# The conformance default stays at the reference's 8 KiB; deployments and
# benches that push large frames through the in-process transport can widen
# it (``Memory.set_duplex_window``) so the window constant — test-infra
# parity, not a behavioral guarantee — doesn't bound throughput.
_duplex_window = _DUPLEX_BUFFER


class _BoundedBuffer:
    """A bounded in-process byte pipe with real backpressure: writers
    block while ``size >= capacity`` (parity with the reference's 8192-byte
    duplex halves — a fast producer cannot grow memory unboundedly).

    Storage is a deque of immutable byte chunks, not a flat bytearray:
    a write appends (at most one copy, from the caller's possibly-reused
    buffer), and ``read_some`` pops a whole chunk with ZERO copies — the
    reader's whole-chunk scan path then parses frames out of that very
    object, so a frame's bytes are copied once end-to-end through the
    in-process transport instead of four times."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity if capacity is not None else _duplex_window
        self._chunks: "deque" = deque()
        self._size = 0
        self._eof = False
        self._cond = asyncio.Condition()

    async def write(self, data) -> None:
        async with self._cond:
            # Chunk so a frame larger than the capacity still flows.
            view = memoryview(data)
            n = len(view)
            off = 0
            while off < n:
                while self._size >= self.capacity and not self._eof:
                    await self._cond.wait()
                if self._eof:
                    raise ConnectionResetError("memory stream closed")
                room = max(self.capacity - self._size, 1)
                piece = bytes(view[off:off + room])  # detach: caller's
                off += len(piece)                    # buffer may be reused
                self._chunks.append(piece)
                self._size += len(piece)
                self._cond.notify_all()

    async def read_exactly(self, n: int) -> bytes:
        # Consume incrementally: n may exceed the buffer capacity (a frame
        # bigger than the duplex window streams through it).
        out = bytearray()
        async with self._cond:
            while len(out) < n:
                if not self._chunks:
                    if self._eof:
                        raise asyncio.IncompleteReadError(bytes(out), n)
                    await self._cond.wait()
                    continue
                head = self._chunks[0]
                take = n - len(out)
                if len(head) <= take:
                    self._chunks.popleft()
                    out += head
                else:
                    out += head[:take]
                    self._chunks[0] = head[take:]
                self._size -= min(take, len(head))
                self._cond.notify_all()
            return bytes(out)

    async def read_some(self, max_n: int) -> bytes:
        async with self._cond:
            while not self._chunks:
                if self._eof:
                    raise asyncio.IncompleteReadError(b"", 1)
                await self._cond.wait()
            head = self._chunks[0]
            if len(head) <= max_n:
                # whole-chunk take: zero copies
                self._chunks.popleft()
                self._size -= len(head)
            else:
                self._chunks[0] = head[max_n:]
                head = head[:max_n]
                self._size -= max_n
            self._cond.notify_all()
            return head

    def set_eof(self) -> None:
        self._eof = True
        # May be called from sync context (abort); schedule the wakeup.
        async def _notify():
            async with self._cond:
                self._cond.notify_all()
        try:
            asyncio.get_running_loop().create_task(_notify())
        except RuntimeError:
            pass


class _PipeStream(RawStream):
    """One side of an in-process duplex over two bounded buffers."""

    def __init__(self, rx: _BoundedBuffer, tx: _BoundedBuffer):
        self._rx = rx
        self._tx = tx
        self._closed = False

    async def read_exactly(self, n: int) -> bytes:
        return await self._rx.read_exactly(n)

    async def read_some(self, max_n: int) -> bytes:
        return await self._rx.read_some(max_n)

    async def write(self, data) -> None:
        if self._closed:
            raise ConnectionResetError("memory stream closed")
        await self._tx.write(data)  # the buffer detaches per chunk itself

    async def close(self) -> None:
        self.abort()

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            self._tx.set_eof()
            self._rx.set_eof()


def _duplex() -> Tuple[_PipeStream, _PipeStream]:
    ab, ba = _BoundedBuffer(), _BoundedBuffer()
    return _PipeStream(rx=ba, tx=ab), _PipeStream(rx=ab, tx=ba)


class _Registry:
    """Process-global endpoint → listener map (parity: the reference's
    ``OnceLock<RwLock<HashMap<String, ChannelExchange>>>``, memory.rs:32-36)."""

    def __init__(self):
        self.listeners: Dict[str, "MemoryListener"] = {}


_REGISTRY = _Registry()


class _MemoryUnfinalized(UnfinalizedConnection):
    def __init__(self, stream: _PipeStream):
        self._stream = stream

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        return Connection(self._stream, limiter, label="memory")


class MemoryListener(Listener):
    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self._accept_q: "asyncio.Queue[_PipeStream]" = asyncio.Queue()
        self._closed = False

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        stream = await self._accept_q.get()
        return _MemoryUnfinalized(stream)

    async def close(self) -> None:
        self._closed = True
        _REGISTRY.listeners.pop(self.endpoint, None)


class Memory(Protocol):
    """The in-process transport (parity protocols/memory.rs)."""

    name = "memory"

    @staticmethod
    def set_duplex_window(capacity: int) -> int:
        """Set the duplex-buffer capacity used by subsequently-created
        connections; returns the previous value. 8192 (the reference
        constant) is the default."""
        global _duplex_window
        prev = _duplex_window
        _duplex_window = capacity
        return prev

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        listener = _REGISTRY.listeners.get(endpoint)
        if listener is None or listener._closed:
            bail(ErrorKind.CONNECTION, f"no memory listener bound at {endpoint!r}")
        ours, theirs = _duplex()
        await listener._accept_q.put(theirs)
        return Connection(ours, limiter, label=f"memory:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str, certificate=None,
                   reuse_port: bool = False) -> Listener:
        if reuse_port:
            bail(ErrorKind.CONNECTION,
                 "memory transport has no kernel socket to SO_REUSEPORT")
        if endpoint in _REGISTRY.listeners:
            bail(ErrorKind.CONNECTION, f"memory endpoint {endpoint!r} already bound")
        listener = MemoryListener(endpoint)
        _REGISTRY.listeners[endpoint] = listener
        return listener


# -- geo-shaped links (ISSUE 11) ---------------------------------------
#
# Consensus-shaped workloads need WAN-ish links between in-process nodes:
# propagation delay, jitter, and loss. The memory transport is a reliable
# ordered stream (like the QUIC transport above it), so "loss" is modeled
# the way a reliable stream experiences it — a retransmit (RTO) delay
# penalty on the affected chunk, never a dropped or reordered byte.
# Delivery times are monotone per direction (a delayed chunk delays
# everything behind it), so stream ordering is preserved by construction.


class LinkShape:
    """One direction's shaping parameters. ``latency_s`` is the one-way
    propagation delay, ``jitter_s`` a uniform [0, jitter) addition,
    ``loss`` the per-chunk probability of a modeled retransmit costing
    ``rto_s`` extra. ``seed`` makes every connection's delay sequence
    deterministic."""

    __slots__ = ("latency_s", "jitter_s", "loss", "rto_s", "seed")

    def __init__(self, latency_s: float = 0.0, jitter_s: float = 0.0,
                 loss: float = 0.0, rto_s: float = 0.05, seed: int = 0):
        self.latency_s = latency_s
        self.jitter_s = jitter_s
        self.loss = loss
        self.rto_s = rto_s
        self.seed = seed

    def __repr__(self):
        return (f"LinkShape(latency_s={self.latency_s}, "
                f"jitter_s={self.jitter_s}, loss={self.loss}, "
                f"rto_s={self.rto_s}, seed={self.seed})")


class _ShapedStream(RawStream):
    """Write-side shaping wrapper: each written chunk is released into the
    underlying pipe at ``max(prev_release, now + delay)`` by a pump task —
    pipelined (a burst pays the latency once, not per chunk) and ordered
    (release times are monotone)."""

    def __init__(self, inner: _PipeStream, shape: LinkShape,
                 rng: random.Random):
        self._inner = inner
        self._shape = shape
        self._rng = rng
        self._q: "asyncio.Queue" = asyncio.Queue(maxsize=64)
        self._pump_task = None
        self._release_at = 0.0
        self._closed = False

    async def read_exactly(self, n: int) -> bytes:
        return await self._inner.read_exactly(n)

    async def read_some(self, max_n: int) -> bytes:
        return await self._inner.read_some(max_n)

    async def write(self, data) -> None:
        if self._closed:
            raise ConnectionResetError("memory stream closed")
        loop = asyncio.get_running_loop()
        if self._pump_task is None:
            self._pump_task = loop.create_task(self._pump())
        sh = self._shape
        delay = sh.latency_s
        if sh.jitter_s:
            delay += self._rng.random() * sh.jitter_s
        if sh.loss and self._rng.random() < sh.loss:
            delay += sh.rto_s  # modeled retransmit on a reliable stream
        release = max(self._release_at, loop.time() + delay)
        self._release_at = release
        # detach now: the deferred write outlives the caller's buffer
        await self._q.put((release, bytes(data)))

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                release, data = await self._q.get()
                dt = release - loop.time()
                if dt > 0:
                    await asyncio.sleep(dt)
                await self._inner.write(data)
                self._q.task_done()
        except (ConnectionResetError, asyncio.CancelledError):
            pass

    async def close(self) -> None:
        # let queued (in-flight) chunks land before tearing the pipe down
        if self._pump_task is not None and not self._closed:
            try:
                await asyncio.wait_for(self._q.join(), timeout=5.0)
            except asyncio.TimeoutError:
                pass
        self.abort()

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            if self._pump_task is not None:
                self._pump_task.cancel()
            self._inner.abort()


_shaped_conn_counter = itertools.count()


def shaped_memory(shape: LinkShape) -> type:
    """A :class:`Memory` subclass whose connections traverse ``shape`` in
    BOTH directions. Pass it as ``ClientConfig.protocol`` so every
    (re)connect of that client stays shaped — per-client geography, no
    global state. Listeners bound via the plain :class:`Memory` accept
    shaped peers transparently (the shaping rides the connecting side's
    stream pair)."""

    link_shape = shape

    class ShapedMemory(Memory):
        name = f"memory+shaped({shape.latency_s * 1e3:g}ms)"
        _shape = link_shape

        @classmethod
        async def connect(cls, endpoint: str, use_local_authority: bool = True,
                          limiter: Limiter = NO_LIMIT) -> Connection:
            listener = _REGISTRY.listeners.get(endpoint)
            if listener is None or listener._closed:
                bail(ErrorKind.CONNECTION,
                     f"no memory listener bound at {endpoint!r}")
            n = next(_shaped_conn_counter)
            ours, theirs = _duplex()
            # independent deterministic streams per direction
            rng_c2s = random.Random((link_shape.seed << 21) ^ (2 * n))
            rng_s2c = random.Random((link_shape.seed << 21) ^ (2 * n + 1))
            await listener._accept_q.put(
                _ShapedStream(theirs, link_shape, rng_s2c))
            return Connection(_ShapedStream(ours, link_shape, rng_c2s),
                              limiter, label=f"memory+shaped:{endpoint}")

    return ShapedMemory


async def gen_testing_connection_pair(limiter: Limiter = NO_LIMIT
                                      ) -> Tuple[Connection, Connection]:
    """Directly build a connected pair (parity ``gen_testing_connection``,
    memory.rs — used heavily by the broker injection harness)."""
    a, b = _duplex()
    return Connection(a, limiter, "memory:a"), Connection(b, limiter, "memory:b")
