"""Transport protocols (reference layer L0).

Every transport reduces to an async byte-stream pair behind the uniform
:class:`~pushcdn_tpu.proto.transport.base.Connection` handle (parity
cdn-proto/src/connection/protocols/mod.rs:85-306). Implementations:

- ``memory`` — in-process duplex streams behind a global registry (test
  infra; parity protocols/memory.rs)
- ``tcp`` — plain TCP with TCP_NODELAY (parity protocols/tcp.rs)
- ``tcp_tls`` — TLS over TCP with the local/prod CA scheme (parity
  protocols/tcp_tls.rs)
- ``quic`` — QUIC-class reliable stream over UDP: handshake, single
  bootstrapped bidirectional stream, ACK/retransmit loss recovery, 5 s
  keep-alive, 3 s graceful finish (parity protocols/quic.rs)

The device data plane's inter-broker "transport" is NOT one of these: broker
↔ broker fan-out on TPU lowers to XLA collectives over ICI (see
pushcdn_tpu.parallel) while these host transports carry the user edge.
"""

from pushcdn_tpu.proto.transport.base import (  # noqa: F401
    Connection,
    Listener,
    Protocol,
    UnfinalizedConnection,
)
from pushcdn_tpu.proto.transport.memory import Memory  # noqa: F401
from pushcdn_tpu.proto.transport.quic import Quic  # noqa: F401
from pushcdn_tpu.proto.transport.tcp import Tcp  # noqa: F401
from pushcdn_tpu.proto.transport.tcp_tls import TcpTls  # noqa: F401
