"""io_uring data plane: engine, stream, listener, and impl selection.

The asyncio event loop stays the control plane (auth, mesh, discovery,
metrics, timers); this module replaces only the per-connection BYTE
path of the TCP transport with one io_uring per event loop (one per
shard worker, since each worker runs one loop):

- ``UringEngine`` — the per-loop singleton. Owns the ring, an eventfd
  bridged into the loop via ``add_reader`` (the completion drainer), a
  deferred-submit "kick" (every prep issued during one loop tick is
  published with ONE ``io_uring_enter`` — or zero with SQPOLL opt-in),
  the pending-operation table that anchors buffer/owner lifetimes, and
  the fixed-buffer slot map for registered pooled egress buffers.
- ``UringStream`` — a :class:`RawStream` over a connected TCP socket.
  Sends go through a per-stream ordered TX queue flushed as ONE
  linked-SQE chain per flight (IOSQE_IO_LINK preserves byte order in
  the kernel; a whole ``EgressBatch`` flush is one submission), so
  ``write()`` returns immediately like asyncio's transport write and
  only awaits under watermark backpressure. Receives are multishot
  provided-buffer recv with watermark pause/resume. Opt-in
  ``MSG_ZEROCOPY`` defers the buffer/owner-lease release to the
  kernel's F_NOTIF completion — not the send CQE.
- ``UringListener`` — multishot accept feeding the normal
  ``UnfinalizedConnection`` handshake path.

Ordering: io_uring does NOT order independent SQEs on one fd. Byte
order survives because each stream keeps AT MOST ONE send chain in
flight (links execute sequentially; the next chain is prepped only
after the previous one fully completes) and ``Connection._write_mutex``
already serializes the producers. Backpressure: the recv side stops
re-arming past a high watermark (the TCP window then closes, exactly
like asyncio's pause_reading), and the send side parks writers on a
drain waiter past the TX high watermark — which is what the
permit/queue accounting upstream already measures.

Impl selection: ``resolve_io_impl()`` reads ``PUSHCDN_IO_IMPL`` (or
legacy ``PUSHCDN_IO_URING``) / the ``--io-impl`` flag: ``asyncio``
(default), ``uring`` (raise if the kernel refuses), or ``auto``
(demote to asyncio with ONE warning when the probe fails — ENOSYS on
old kernels, EPERM under seccomp). TLS stays on asyncio regardless,
with an honest one-time log line.
"""

from __future__ import annotations

import asyncio
import ctypes
import errno
import logging
import os
import socket
import weakref
from collections import deque
from typing import Optional

import numpy as np

from pushcdn_tpu.native import uring as nuring
from pushcdn_tpu.proto import metrics as metrics_mod
from pushcdn_tpu.proto.transport.base import (
    Connection,
    Listener,
    RawStream,
    UnfinalizedConnection,
)
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT

log = logging.getLogger("pushcdn.uring")

# -- io impl selection -------------------------------------------------------

IO_IMPLS = ("auto", "uring", "asyncio")
_resolved: Optional[str] = None
_warned_demote = False
_warned_tls = False


def configured_io_impl() -> str:
    """The REQUESTED impl: ``PUSHCDN_IO_IMPL`` (auto|uring|asyncio; the
    ``--io-impl`` flag writes this env so shard workers and spawned
    helpers inherit it), legacy ``PUSHCDN_IO_URING`` (1/0/auto), else
    ``asyncio`` — the engine is opt-in this round; flip the default
    after a soak."""
    v = os.environ.get("PUSHCDN_IO_IMPL", "").strip().lower()
    if v in IO_IMPLS:
        return v
    u = os.environ.get("PUSHCDN_IO_URING", "").strip().lower()
    if u in ("1", "true", "yes", "uring"):
        return "uring"
    if u in ("auto",):
        return "auto"
    return "asyncio"


def set_io_impl(impl: str) -> None:
    """Select the io impl for this process AND its children (the env is
    what ``--shards`` worker processes inherit)."""
    global _resolved
    if impl not in IO_IMPLS:
        raise ValueError(f"io impl must be one of {IO_IMPLS}, got {impl!r}")
    os.environ["PUSHCDN_IO_IMPL"] = impl
    _resolved = None  # re-resolve lazily


def resolve_io_impl() -> str:
    """Resolve auto/uring/asyncio → the impl actually in use ("uring" or
    "asyncio"), probing the kernel once. ``auto`` demotes with one
    warning; explicit ``uring`` raises instead of mislabeling."""
    global _resolved, _warned_demote
    if _resolved is not None:
        return _resolved
    req = configured_io_impl()
    if req == "asyncio":
        _resolved = "asyncio"
    elif nuring.available():
        _resolved = "uring"
    elif req == "uring":
        raise nuring.RingError(
            -min(nuring.probe(), -1),
            f"--io-impl uring requested but io_uring is unavailable "
            f"({nuring.probe_errname()})")
    else:  # auto → honest demotion
        if not _warned_demote:
            _warned_demote = True
            log.warning(
                "io_uring unavailable (%s): --io-impl auto demoted to "
                "asyncio", nuring.probe_errname())
        _resolved = "asyncio"
    try:
        metrics_mod.IO_IMPL.labels(impl=_resolved).set(1)
    except Exception:
        pass
    return _resolved


def warn_tls_fallback_once() -> None:
    """tcp+tls keeps the asyncio path (no kTLS offload here — Python's
    ssl module owns the record layer, so the kernel never sees
    plaintext to send): say so once instead of silently ignoring the
    knob."""
    global _warned_tls
    if not _warned_tls and resolve_io_impl() == "uring":
        _warned_tls = True
        log.warning("io-impl uring: tcp+tls stays on asyncio "
                    "(ssl owns the record layer; no kTLS)")


# -- buffer address helpers --------------------------------------------------

def _addr_of(data):
    """(addr, nbytes, keepalive) without copying. ``bytes`` resolves via
    c_char_p (no buffer export); bytearray/memoryview go through a
    numpy view (the keepalive tuple pins both the exporter and the
    view). The engine holds ``keepalive`` until the terminal CQE, so
    the kernel never reads freed or recycled memory."""
    if type(data) is bytes:
        return (ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value,
                len(data), data)
    arr = np.frombuffer(data, np.uint8)
    return int(arr.ctypes.data), arr.nbytes, (data, arr)


def _base_of(data):
    """The ultimate exporting object of a (possibly chained) memoryview
    — the identity the fixed-buffer slot map is keyed on."""
    base = data
    while isinstance(base, memoryview):
        base = base.obj
    return base


# -- engine ------------------------------------------------------------------

_SQ_ENTRIES = int(os.environ.get("PUSHCDN_URING_SQ", "1024"))
# 128 x 128 KiB (16 MiB/ring) measured best on the loopback A/B: big
# enough that one CQE carries a whole coalesced flight, small enough
# that the kernel's copy-to-provided-buffer stays cache-friendly
_PBUF_ENTRIES = int(os.environ.get("PUSHCDN_URING_PBUFS", "128"))
_PBUF_LEN = int(os.environ.get("PUSHCDN_URING_PBUF_LEN", str(128 * 1024)))
_FIXED_SLOTS = 16
_RX_HIGH = 256 * 1024  # multishot recv pause watermark (per stream)
_RX_LOW = 64 * 1024
_TX_HIGH = 256 * 1024  # send-queue backpressure watermark (per stream)
_TX_LOW = 64 * 1024
_CHAIN_MAX = 64        # max sends linked into one flight

_ECANCELED = getattr(errno, "ECANCELED", 125)


class _Send:
    """A pending send SQE: anchors the buffer (and ZC owner lease) until
    the kernel is finished with the memory — the terminal CQE, or for
    MSG_ZEROCOPY the F_NOTIF completion that may trail it."""
    __slots__ = ("stream", "keep", "owner", "zc")

    def __init__(self, stream, keep, owner, zc):
        self.stream = stream
        self.keep = keep
        self.owner = owner
        self.zc = zc


def _env_zc_min() -> int:
    try:
        return int(os.environ.get("PUSHCDN_URING_ZC_MIN", "0"))
    except ValueError:
        return 0


# -- native telemetry aggregation (ISSUE 19) ---------------------------------
# Each engine's ring owns one shm telemetry block written from C
# (native/io_uring.cpp). /metrics aggregates: live engines are
# snapshotted at render, closed engines fold their final snapshot into
# this module-level carry so the rendered histograms stay monotonic
# across engine teardown (loop-per-test suites recreate engines freely).
# Default-on; PUSHCDN_NATIVE_TELEMETRY=0 is the bench A/B "off" leg.

def _native_telemetry_enabled() -> bool:
    return os.environ.get("PUSHCDN_NATIVE_TELEMETRY", "1") != "0"


_TELEM_CARRY: Optional[dict] = None


def _tm_empty() -> dict:
    return nuring.parse_telemetry([0] * nuring.TM_WORDS)


def _tm_merge(dst: dict, src: Optional[dict]) -> dict:
    """Accumulate one parsed telemetry snapshot into ``dst`` (all-counter
    payload, so element-wise sums are exact; peer rows concatenate —
    distinct engines never share an fd at the same instant)."""
    if src is None:
        return dst
    for key in ("stage", "chain", "class_delay"):
        for name, h in src[key].items():
            d = dst[key][name]
            d["count"] += h["count"]
            d["sum_ns"] += h["sum_ns"]
            db = d["buckets"]
            for k, c in enumerate(h["buckets"]):
                db[k] += c
    for key in ("class_frames", "class_bytes", "class_drop_frames"):
        for name, v in src.get(key, {}).items():
            dst[key][name] = dst[key].get(name, 0) + v
    dst["peers"].extend(src.get("peers", ()))
    return dst


def telemetry_totals() -> Optional[dict]:
    """Aggregate native telemetry: live engines' snapshots plus the
    closed-engine carry (``parse_telemetry`` shape). None when nothing
    has ever been recorded — the pre-render hook then skips the push."""
    totals: Optional[dict] = None
    if _TELEM_CARRY is not None:
        totals = _tm_merge(_tm_empty(), _TELEM_CARRY)
    for _, eng in UringEngine._engines.values():
        if eng.closed:
            continue
        try:
            snap = eng.ring.telemetry_snapshot()
        except Exception:
            continue
        parsed = nuring.parse_telemetry(snap) if snap is not None else None
        if parsed is not None:
            totals = _tm_merge(totals if totals is not None else _tm_empty(),
                               parsed)
    return totals


def _telemetry_pre_render() -> None:
    metrics_mod.update_native_telemetry(telemetry_totals())


metrics_mod.PRE_RENDER_HOOKS.append(_telemetry_pre_render)


class UringEngine:
    """Per-event-loop io_uring engine. Use :meth:`current`."""

    _engines: dict = {}  # id(loop) -> (weakref(loop), engine)

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.ring = nuring.Ring(
            entries=_SQ_ENTRIES,
            sqpoll=os.environ.get("PUSHCDN_URING_SQPOLL", "") == "1",
            pbuf_entries=_PBUF_ENTRIES, pbuf_len=_PBUF_LEN,
            fixed_slots=_FIXED_SLOTS)
        self._efd = os.eventfd(0, os.EFD_NONBLOCK | os.EFD_CLOEXEC)
        try:
            # NOT async-only: a blocked send finishing in io-wq posts its
            # CQE via task-work (task context), which EVENTFD_ASYNC never
            # signals — a backpressured writer whose peer finally drained
            # would strand until unrelated traffic forced a drain. Inline
            # completions double-signal instead; the post-submit drain
            # makes those wakes cheap no-ops.
            self.ring.register_eventfd(self._efd, async_only=False)
            loop.add_reader(self._efd, self._on_event)
        except BaseException:
            os.close(self._efd)
            self.ring.close()
            raise
        # native telemetry block: stamped from C on the pump/engine hot
        # paths, snapshotted by the /metrics pre-render hook. Best-effort
        # (an mmap failure just leaves telemetry off).
        if _native_telemetry_enabled():
            try:
                self.ring.enable_telemetry()
            except Exception:
                pass
        self._pending: dict = {}
        self._next_ud = 0
        self._kick_scheduled = False
        self._need_submit = False
        self.closed = False
        # fused data-plane pump (transport/pump.py): ONE per engine,
        # claimed by the first RouteState on this loop
        self.pump_state = None
        # fixed-buffer registration: id(buffer) -> slot, with strong refs
        # so a registered buffer's pages can never be freed while the
        # kernel holds the pin
        self._fixed: dict = {}
        self._fixed_keep: list = []
        self.zc_min = _env_zc_min()
        self.zc_ok = self.zc_min > 0 and nuring.zerocopy_supported()
        self.fixed_ok = self.ring.fixed_slots > 0
        # counters for the bench's attribution row and /debug
        self.sqes = 0
        self.cqes = 0
        self.wakes = 0
        self.zc_sends = 0
        self.zc_notifs = 0
        # register every pooled egress buffer that already exists, and
        # hook future pool growth (registration is once per buffer, not
        # per send)
        try:
            from pushcdn_tpu import native as _native
            for buf in _native.egress_pool_buffers():
                self.register_fixed_buffer(buf)
            _native.add_egress_registrar(self._registrar_ref())
        except Exception:
            pass

    # -- lifecycle --

    @classmethod
    def current(cls) -> "UringEngine":
        """The engine for the running loop (created on first use).
        Sweeps engines whose loops have died — fd hygiene for
        loop-per-test suites."""
        loop = asyncio.get_running_loop()
        key = id(loop)
        for k, (ref, eng) in list(cls._engines.items()):
            lp = ref()
            if lp is None or (lp is not loop and lp.is_closed()):
                eng.close()
                cls._engines.pop(k, None)
        ent = cls._engines.get(key)
        if ent is not None:
            eng = ent[1]
            if not eng.closed:
                return eng
            cls._engines.pop(key, None)
        eng = cls(loop)
        cls._engines[key] = (weakref.ref(loop), eng)
        return eng

    @classmethod
    def shutdown(cls, loop=None) -> None:
        """Close the engine bound to ``loop`` (default: every engine).
        Tests and bins call this for deterministic fd/lease cleanup."""
        if loop is not None:
            ent = cls._engines.pop(id(loop), None)
            if ent:
                ent[1].close()
            return
        for _, (ref, eng) in list(cls._engines.items()):
            eng.close()
        cls._engines.clear()

    def _registrar_ref(self):
        selfref = weakref.ref(self)

        def _register(buf):
            eng = selfref()
            if eng is not None and not eng.closed:
                eng.register_fixed_buffer(buf)
        return _register

    def close(self) -> None:
        """Tear the engine down: fail every pending op, release every
        buffer/owner keep-alive (zero leaked leases), close the ring —
        the kernel cancels in-flight SQEs when the ring fd drops."""
        if self.closed:
            return
        self.closed = True
        try:
            self._loop.remove_reader(self._efd)
        except Exception:
            pass
        ps = self.pump_state
        if ps is not None:
            # the pump preps SQEs on this ring's mapped memory — it
            # must die before the ring fd drops
            try:
                ps.engine_dead()
            except Exception:
                pass
        dead: list = []
        for ud, e in list(self._pending.items()):
            if isinstance(e, _Send):
                e.keep = e.owner = None
                if e.stream is not None:
                    dead.append(e.stream)
            elif isinstance(e, (UringStream, UringListener)):
                dead.append(e)
        self._pending.clear()
        seen: set = set()
        for obj in dead:
            if id(obj) not in seen:
                seen.add(id(obj))
                obj._engine_dead()
        self._fixed.clear()
        self._fixed_keep.clear()
        try:
            os.close(self._efd)
        except OSError:
            pass
        # fold the final telemetry snapshot into the module carry BEFORE
        # the ring drops (pcu_destroy munmaps the block) so the rendered
        # aggregates stay monotonic across engine teardown
        global _TELEM_CARRY
        try:
            snap = self.ring.telemetry_snapshot()
            if snap is not None:
                _TELEM_CARRY = _tm_merge(
                    _TELEM_CARRY if _TELEM_CARRY is not None
                    else _tm_empty(),
                    nuring.parse_telemetry(snap))
        except Exception:
            pass
        self.ring.close()

    def stats(self) -> dict:
        return {"enters": self.ring.enters, "sqes": self.sqes,
                "cqes": self.cqes, "wakes": self.wakes,
                "zc_sends": self.zc_sends, "zc_notifs": self.zc_notifs,
                "pending": len(self._pending),
                "fixed_slots": len(self._fixed)}

    # -- fixed buffers --

    def register_fixed_buffer(self, buf) -> Optional[int]:
        """Register a pooled egress buffer into a fixed slot (page pin
        done ONCE; sends then use WRITE_FIXED / SEND_ZC+FIXED_BUF).
        Bounded by the sparse table size; silently skipped beyond it.
        The engine keeps a strong ref: a registered buffer that later
        leaves the pool stays pinned rather than dangling."""
        if not self.fixed_ok or self.closed:
            return None
        key = id(buf)
        slot = self._fixed.get(key)
        if slot is not None:
            return slot
        if len(self._fixed) >= self.ring.fixed_slots:
            return None
        try:
            arr = np.frombuffer(buf, np.uint8)
        except (TypeError, ValueError, BufferError):
            return None
        slot = len(self._fixed)
        if self.ring.update_fixed(slot, int(arr.ctypes.data),
                                  arr.nbytes) != 0:
            self.fixed_ok = False  # RLIMIT_MEMLOCK etc: stop trying
            return None
        self._fixed[key] = slot
        self._fixed_keep.append((buf, arr))
        return slot

    def fixed_slot_for(self, data) -> int:
        if not self._fixed:
            return -1
        return self._fixed.get(id(_base_of(data)), -1)

    # -- submit plumbing --

    def _ud(self) -> int:
        self._next_ud += 1
        return self._next_ud

    def _schedule_kick(self) -> None:
        if not self._kick_scheduled and not self.closed:
            self._kick_scheduled = True
            self._loop.call_soon(self._kick)

    def _kick(self) -> None:
        """Publish every SQE prepped this loop tick with one enter, then
        drain completions. Completion handlers prep follow-up SQEs (the
        next TX chain, multishot rearms) — the loop re-submits those in
        the SAME tick so loopback/buffered chains progress without
        waiting for another event-loop pass. Bounded as a guard; real
        chains converge in a few rounds."""
        self._kick_scheduled = False
        if self.closed:
            return
        for _ in range(64):
            self._need_submit = False
            try:
                self.ring.submit()
            except nuring.RingError as exc:
                log.error("io_uring submit failed: %s", exc)
                self.close()
                return
            self._drain()
            if self.closed or not self._need_submit:
                return
        self._schedule_kick()

    def _on_event(self) -> None:
        try:
            os.read(self._efd, 8)
        except (BlockingIOError, OSError):
            pass
        self.wakes += 1
        if self.closed:
            return
        self._drain()
        if self._need_submit and not self.closed:
            self._kick()

    def _drain(self) -> None:
        ps = self.pump_state
        if ps is not None and not ps.closed:
            # pump-aware drain: native code walks the CQ, consumes
            # pump-tagged CQEs (chain advance + starved-chain prep) and
            # hands everything else back for the dispatch below
            ps.drain()
            return
        ring = self.ring
        while True:
            cqes = ring.peek_cqes()
            if not cqes:
                break
            self.cqes += len(cqes)
            for ud, res, flags in cqes:
                self._complete(ud, res, flags)
                if self.closed:
                    return

    def _complete(self, ud: int, res: int, flags: int) -> None:
        e = self._pending.get(ud)
        if e is None:
            # completion for a dead owner: recycle any selected buffer
            if flags & nuring.CQE_F_BUFFER:
                self.ring.pbuf_recycle(
                    (flags >> nuring.CQE_BUFFER_SHIFT) & 0xFFFF)
            return
        if isinstance(e, _Send):
            if flags & nuring.CQE_F_NOTIF:
                # kernel done with the ZC pages: NOW the lease drops
                del self._pending[ud]
                e.keep = e.owner = None
                self.zc_notifs += 1
                return
            if e.zc and (flags & nuring.CQE_F_MORE):
                stream, e.stream = e.stream, None  # entry stays for NOTIF
            else:
                del self._pending[ud]
                stream = e.stream
                e.keep = e.owner = None
            if stream is not None:
                stream._on_send_cqe(res)
        elif isinstance(e, UringStream):
            terminal = not (flags & nuring.CQE_F_MORE)
            data = None
            if flags & nuring.CQE_F_BUFFER:
                bid = (flags >> nuring.CQE_BUFFER_SHIFT) & 0xFFFF
                if res > 0:
                    data = self.ring.pbuf_read(bid, res)
                self.ring.pbuf_recycle(bid)
            if terminal:
                del self._pending[ud]
            e._on_recv_cqe(ud, res, data, terminal)
        elif isinstance(e, UringListener):
            terminal = not (flags & nuring.CQE_F_MORE)
            if terminal:
                del self._pending[ud]
            e._on_accept_cqe(ud, res, terminal)
        else:  # cancel / shutdown markers
            del self._pending[ud]

    # -- op submission (streams/listeners call these) --

    def prep_stream_send(self, stream, fd: int, addr: int, length: int,
                         keep, owner, zc: bool, buf_index: int,
                         link: bool) -> None:
        """One send SQE for a stream TX entry; ``link`` chains it to the
        NEXT prepped SQE (in-kernel ordering for a multi-buffer flight)."""
        ud = self._ud()
        self._pending[ud] = _Send(stream, keep, owner, zc)
        sqe_flags = nuring.IOSQE_IO_LINK if link else 0
        msg_flags = nuring.MSG_NOSIGNAL | nuring.MSG_WAITALL
        if zc:
            self.ring.prep_send_zc(fd, addr, length, ud, buf_index,
                                   sqe_flags, msg_flags)
            self.zc_sends += 1
        elif buf_index >= 0:
            self.ring.prep_write_fixed(fd, addr, length, buf_index, ud,
                                       sqe_flags)
        else:
            self.ring.prep_send(fd, addr, length, ud, sqe_flags, msg_flags)
        self.sqes += 1
        self._need_submit = True
        self._schedule_kick()

    def arm_recv(self, stream: "UringStream") -> int:
        ud = self._ud()
        self._pending[ud] = stream
        self.ring.prep_recv_multishot(stream._fd, ud)
        self.sqes += 1
        self._need_submit = True
        self._schedule_kick()
        return ud

    def arm_accept(self, listener: "UringListener") -> int:
        ud = self._ud()
        self._pending[ud] = listener
        self.ring.prep_accept_multishot(listener._fd, ud)
        self.sqes += 1
        self._need_submit = True
        self._schedule_kick()
        return ud

    def cancel_op(self, target_ud: int) -> None:
        if self.closed:
            return
        cud = self._ud()
        self._pending[cud] = "cancel"
        self.ring.prep_cancel(target_ud, cud)
        self.sqes += 1
        self._need_submit = True
        self._schedule_kick()


# -- stream ------------------------------------------------------------------

# TX queue entry indices (a list, mutated in place). ADDR/KEEP/BIDX are
# resolved lazily at pump time: a coalesce bytearray may still be
# EXTENDED while queued (realloc moves it), so pinning the address early
# would dangle.
(_T_DATA, _T_LEN, _T_SENT, _T_OWNER, _T_ZC, _T_COAL,
 _T_KEEP, _T_ADDR, _T_BIDX) = range(9)

_COAL_ENTRY_MAX = 64 * 1024   # plain sends up to this coalesce...
_COAL_BUF_MAX = 256 * 1024    # ...into shared buffers up to this


class UringStream(RawStream):
    """RawStream over a connected socket, driven by the loop's
    UringEngine. ``wants_owner`` tells the Connection flush paths to
    hand the PreEncoded owner lease down, enabling ZC deferral."""

    wants_owner = True

    def __init__(self, sock: socket.socket, engine: UringEngine):
        self._sock = sock
        self._fd = sock.fileno()
        self._engine = engine
        # receive side
        self._rx: deque = deque()
        self._rx_head = 0
        self._rx_bytes = 0
        self._rx_err: Optional[BaseException] = None
        self._eof = False
        self._paused = False
        self._waiter: Optional[asyncio.Future] = None
        self._recv_ud: Optional[int] = None
        self._recv_terminal: Optional[asyncio.Future] = None
        # send side: ordered queue; the first _tx_flight entries are in
        # the kernel as one linked chain
        self._tx: deque = deque()
        self._tx_bytes = 0
        self._tx_flight = 0
        self._tx_err: Optional[BaseException] = None
        self._tx_waiter: Optional[asyncio.Future] = None
        self._tx_idle: Optional[asyncio.Future] = None
        self._closed = False
        # fused pump (transport/pump.py): set when this stream is
        # pump-engaged (binding) or engagement is pending (state)
        self._pump_state = None
        self._pump_binding = None
        self._arm()

    # -- receive plumbing (engine callbacks) --

    def _arm(self) -> None:
        if self._closed or self._eof or self._rx_err is not None \
                or self._recv_ud is not None:
            return
        self._recv_ud = self._engine.arm_recv(self)

    def _on_recv_cqe(self, ud: int, res: int, data, terminal: bool) -> None:
        # data CQEs between a pause-cancel and its terminal completion are
        # REAL in-order bytes and must be kept — only a closed stream
        # drops them (its fd is on the way out, matching asyncio's
        # close-tears-down-both-sides semantics)
        if res > 0 and data and not self._closed:
            self._rx.append(data)
            self._rx_bytes += len(data)
            self._wake()
            if self._rx_bytes >= _RX_HIGH and not self._paused \
                    and self._recv_ud is not None:
                # backpressure: stop pulling bytes; the kernel socket
                # buffer fills and the peer's TCP window closes
                self._paused = True
                self._engine.cancel_op(self._recv_ud)
        elif res == 0:
            self._eof = True
            self._wake()
        elif res < 0 and res not in (-_ECANCELED, -errno.ENOBUFS):
            self._rx_err = ConnectionResetError(-res, os.strerror(-res))
            self._wake()
        if terminal:
            if ud == self._recv_ud:
                self._recv_ud = None
            if self._recv_terminal is not None \
                    and not self._recv_terminal.done():
                self._recv_terminal.set_result(None)
            if not self._paused:
                self._arm()  # ENOBUFS / !F_MORE rearm (bufs recycled)

    def _wake(self) -> None:
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)
        self._waiter = None

    def _engine_dead(self) -> None:
        self._pump_binding = None
        self._pump_state = None
        self._recv_ud = None
        if self._rx_err is None and not self._eof:
            self._rx_err = ConnectionResetError(
                errno.EBADF, "uring engine closed")
        if self._tx_err is None:
            self._tx_fail(ConnectionResetError(
                errno.EBADF, "uring engine closed"))
        self._wake()

    def _maybe_resume(self) -> None:
        if self._paused and self._rx_bytes <= _RX_LOW and not self._closed \
                and not self._eof and self._rx_err is None:
            self._paused = False
            # two armed multishots on one fd would interleave and corrupt
            # byte order: rearm only once the cancelled op has fully
            # terminated (otherwise the terminal handler rearms, since
            # _paused is now False)
            if self._recv_ud is None:
                self._arm()

    # -- send plumbing --

    def _queue_tx(self, data, owner) -> None:
        eng = self._engine
        n = len(data)
        zc = (eng.zc_ok and n >= eng.zc_min
              and (type(data) is bytes or owner is not None))
        tx = self._tx
        # Entries that MUST copy: mutable or revocable memory with no
        # owner lease. The writer releases encoder scratch memoryviews
        # (and reuses the underlying buffer) the moment write() returns,
        # and pipelining means the kernel reads LATER — only immutable
        # ``bytes`` (refcount-pinned by the keepalive) and owner-leased
        # views may ride zero-copy. The asyncio stream materializes the
        # same views to bytes, so the copy is parity, not a regression.
        if not zc and (n <= _COAL_ENTRY_MAX
                       or (owner is None and type(data) is not bytes)):
            # coalesce small sends into one buffer, exactly like
            # asyncio's transport write buffer: back-to-back pipelined
            # writes leave as ONE send, so the receiver sees one large
            # completion instead of a CQE per write. The copy also means
            # a small owner-backed entry needs no deferred lease — the
            # caller's refcount releases the pool buffer immediately
            # (asyncio's write path materializes the same way). Only a
            # queued-but-not-in-flight tail may grow (in-flight memory
            # is pinned by the kernel), and only before its address was
            # resolved (a numpy export blocks bytearray resize).
            if len(tx) > self._tx_flight:
                tail = tx[-1]
                if tail[_T_COAL] and tail[_T_KEEP] is None \
                        and tail[_T_LEN] + n <= _COAL_BUF_MAX:
                    tail[_T_DATA] += data
                    tail[_T_LEN] += n
                    self._tx_bytes += n
                    return
            tx.append([bytearray(data), n, 0, None, False, True,
                       None, 0, -1])
        else:
            tx.append([data, n, 0, owner, zc, False, None, 0, -1])
        self._tx_bytes += n

    def _pump(self) -> None:
        """Prep the whole TX queue (up to _CHAIN_MAX entries) as one
        linked chain. Called only when nothing is in flight. Addresses
        resolve here — entries are frozen once in flight."""
        if self._tx_flight or not self._tx or self._tx_err is not None \
                or self._engine.closed:
            return
        eng = self._engine
        n = min(len(self._tx), _CHAIN_MAX)
        for i in range(n):
            e = self._tx[i]
            if e[_T_KEEP] is None:
                addr, _nb, keep = _addr_of(e[_T_DATA])
                e[_T_ADDR] = addr
                e[_T_KEEP] = keep
                e[_T_BIDX] = (eng.fixed_slot_for(e[_T_DATA])
                              if (e[_T_ZC] or eng.fixed_ok) else -1)
            eng.prep_stream_send(
                self, self._fd, e[_T_ADDR] + e[_T_SENT],
                e[_T_LEN] - e[_T_SENT], e[_T_KEEP], e[_T_OWNER],
                e[_T_ZC], e[_T_BIDX] if e[_T_SENT] == 0 else -1,
                link=(i != n - 1))
        self._tx_flight = n

    def _on_send_cqe(self, res: int) -> None:
        """One send CQE of the in-flight chain (in link order)."""
        if self._tx_flight <= 0:
            return  # aborted stream: queue already dropped
        self._tx_flight -= 1
        chain_done = self._tx_flight == 0
        if self._tx_err is None and self._tx:
            e = self._tx[0]
            if res == 0 and e[_T_LEN] > e[_T_SENT]:
                # 0-byte completion on a nonempty send: the peer is gone
                # (re-pumping would spin hot)
                self._tx_fail(ConnectionResetError(
                    errno.EPIPE, "zero-length send completion"))
            elif res >= 0:
                e[_T_SENT] += res
                if e[_T_SENT] >= e[_T_LEN]:
                    self._tx.popleft()
                    self._tx_bytes -= e[_T_LEN]
                elif not chain_done:
                    # a SHORT-but-successful mid-chain send means later
                    # links already wrote past the gap — framing is
                    # unrecoverable, poison (detectable, never silent)
                    self._tx_fail(ConnectionResetError(
                        errno.EIO,
                        f"short linked send ({res}/{e[_T_LEN]})"))
                # else: lone/last entry short (WAITALL backstop):
                # stays at queue head, next pump resubmits the residue
            elif res in (-errno.EINVAL, -errno.EOPNOTSUPP) \
                    and (e[_T_ZC] or e[_T_BIDX] >= 0):
                # kernel refused the fancy path: demote globally and
                # let the next pump retry this entry plain (honest
                # fallback, no mislabel)
                eng = self._engine
                if e[_T_ZC]:
                    eng.zc_ok = False
                if e[_T_BIDX] >= 0:
                    eng.fixed_ok = False
                e[_T_ZC] = False
                e[_T_BIDX] = -1
            elif res == -_ECANCELED:
                pass  # chain tail after a failed link: entry stays queued
            else:
                self._tx_fail(ConnectionResetError(
                    -res, os.strerror(-res)))
        if not chain_done:
            return
        # whole flight accounted: wake writers / pump the next chain
        if self._tx_err is None:
            if self._tx_bytes <= _TX_LOW:
                self._wake_tx(None)
            if self._tx:
                self._pump()
            else:
                if self._tx_idle is not None and not self._tx_idle.done():
                    self._tx_idle.set_result(None)
                ps = self._pump_state
                if ps is not None:
                    # TX-idle transition: the pump's engage/unfence hook
                    ps.on_stream_idle(self)

    def _tx_fail(self, err: BaseException) -> None:
        self._tx_err = err
        self._tx.clear()  # entry keep/owner refs drop (leases release)
        self._tx_bytes = 0
        self._wake_tx(err)
        if self._tx_idle is not None and not self._tx_idle.done():
            self._tx_idle.set_result(None)

    def _wake_tx(self, err: Optional[BaseException]) -> None:
        w = self._tx_waiter
        if w is not None and not w.done():
            if err is None:
                w.set_result(None)
            else:
                w.set_exception(err)
        self._tx_waiter = None

    async def _tx_drain(self) -> None:
        """Park until the TX queue falls below the low watermark — the
        io_uring twin of asyncio's ``drain()``. The connection's write
        timeout wraps this, so a stalled peer still poisons."""
        while self._tx_bytes > _TX_HIGH and self._tx_err is None \
                and not self._closed:
            if self._tx_waiter is None:
                self._tx_waiter = \
                    asyncio.get_running_loop().create_future()
            await asyncio.shield(self._tx_waiter)
        if self._tx_err is not None:
            raise self._tx_err

    # -- RawStream API --

    async def read_some(self, max_n: int) -> bytes:
        while True:
            if self._rx:
                head = self._rx[0]
                avail = len(head) - self._rx_head
                if avail > max_n:
                    chunk = head[self._rx_head:self._rx_head + max_n]
                    self._rx_head += max_n
                    self._rx_bytes -= max_n
                    self._maybe_resume()
                    return chunk
                self._rx.popleft()
                chunk = head[self._rx_head:] if self._rx_head else head
                self._rx_head = 0
                got = len(chunk)
                if not self._rx or got == max_n:
                    self._rx_bytes -= got
                    self._maybe_resume()
                    return chunk
                # gather queued completions into ONE return, like asyncio
                # returning its whole accumulated transport buffer: the
                # parser upstream then sees big contiguous spans instead
                # of one span per CQE
                parts = [chunk]
                while self._rx and got < max_n:
                    nxt = self._rx[0]
                    if got + len(nxt) <= max_n:
                        self._rx.popleft()
                        parts.append(nxt)
                        got += len(nxt)
                    else:
                        take = max_n - got
                        parts.append(nxt[:take])
                        self._rx_head = take
                        got = max_n
                self._rx_bytes -= got
                self._maybe_resume()
                return b"".join(parts)
            if self._rx_err is not None:
                raise self._rx_err
            if self._eof:
                raise asyncio.IncompleteReadError(b"", 1)
            if self._closed:
                raise ConnectionResetError(errno.EBADF, "stream closed")
            if self._waiter is None:
                self._waiter = \
                    asyncio.get_running_loop().create_future()
            await asyncio.shield(self._waiter)

    async def read_exactly(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += await self.read_some(n - len(out))
        return bytes(out)

    async def write(self, data, owner=None) -> None:
        if self._tx_err is not None:
            raise self._tx_err
        if self._closed:
            raise ConnectionResetError(errno.EBADF, "stream closed")
        if len(data) == 0:
            return
        b = self._pump_binding
        if b is not None:
            # fence + wait out queued native runs: a Python write must
            # never interleave with a pumped chain on the same fd
            await b.write_gate()
            if self._tx_err is not None:
                raise self._tx_err
            if self._closed:
                raise ConnectionResetError(errno.EBADF, "stream closed")
        self._queue_tx(data, owner)
        if not self._tx_flight:
            self._pump()
        if self._tx_bytes > _TX_HIGH:
            await self._tx_drain()

    async def writev(self, bufs, owner=None) -> None:
        if self._tx_err is not None:
            raise self._tx_err
        if self._closed:
            raise ConnectionResetError(errno.EBADF, "stream closed")
        b = self._pump_binding
        if b is not None:
            await b.write_gate()
            if self._tx_err is not None:
                raise self._tx_err
            if self._closed:
                raise ConnectionResetError(errno.EBADF, "stream closed")
        queued = False
        for b in bufs:
            if len(b):
                self._queue_tx(b, owner)
                queued = True
        if queued and not self._tx_flight:
            self._pump()
        if self._tx_bytes > _TX_HIGH:
            await self._tx_drain()

    async def close(self) -> None:
        if self._closed:
            return
        eng = self._engine
        b = self._pump_binding
        if b is not None:
            # let queued native runs reach the wire before the FIN
            await b.quiesce_and_drop()
            if self._closed:
                return
        # flush: wait for the TX queue to drain (bounded) before FIN —
        # asyncio's close() flushes its transport buffer the same way
        if (self._tx or self._tx_flight) and self._tx_err is None \
                and not eng.closed:
            self._tx_idle = eng._loop.create_future()
            if not self._tx_flight:
                self._pump()
            try:
                await asyncio.wait_for(asyncio.shield(self._tx_idle), 5.0)
            except (asyncio.TimeoutError, Exception):
                pass
        self._closed = True
        # a parked multishot recv holds a kernel file reference — the
        # socket would never actually close (no FIN) under it. Cancel,
        # wait for the terminal CQE, then close the fd.
        if self._recv_ud is not None and not eng.closed:
            self._recv_terminal = eng._loop.create_future()
            eng.cancel_op(self._recv_ud)
            try:
                await asyncio.wait_for(
                    asyncio.shield(self._recv_terminal), 1.0)
            except (asyncio.TimeoutError, Exception):
                pass
        self._wake()
        try:
            self._sock.close()
        except OSError:
            pass

    def abort(self) -> None:
        if self._closed:
            return
        self._closed = True
        b = self._pump_binding
        if b is not None:
            b.drop_now()
        # drop everything queued but not yet in flight (their lease refs
        # release); in-flight entries stay anchored by the engine's
        # pending table until their terminal CQEs
        self._tx.clear()
        self._tx_bytes = 0
        if self._tx_err is None:
            self._tx_err = ConnectionResetError(
                errno.ECONNRESET, "stream aborted")
        self._wake_tx(self._tx_err)
        # shutdown() tears the connection down regardless of the file
        # refs in-flight SQEs hold; the armed recv then completes (EOF /
        # reset), and the terminal CQE path below closes the fd.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        eng = self._engine
        if self._recv_ud is not None and not eng.closed:
            sock = self._sock
            self._recv_terminal = eng._loop.create_future()
            self._recv_terminal.add_done_callback(
                lambda _f: _close_quiet(sock))
            eng.cancel_op(self._recv_ud)
        else:
            _close_quiet(self._sock)
        if self._rx_err is None and not self._eof:
            self._rx_err = ConnectionResetError(
                errno.ECONNRESET, "stream aborted")
        self._wake()


def _close_quiet(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass


# -- listener / protocol glue ------------------------------------------------

class _UringUnfinalized(UnfinalizedConnection):
    def __init__(self, sock: socket.socket, engine: UringEngine,
                 label: str):
        self._sock = sock
        self._engine = engine
        self._label = label

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        return Connection(UringStream(self._sock, self._engine), limiter,
                          label=self._label)


class UringListener(Listener):
    """Multishot-accept listener: ONE armed SQE accepts every incoming
    connection; the CQE drainer enqueues accepted fds here."""

    def __init__(self, sock: socket.socket, engine: UringEngine):
        self._sock = sock
        self._fd = sock.fileno()
        self._engine = engine
        self._accepted: deque = deque()
        self._waiter: Optional[asyncio.Future] = None
        self._closed = False
        self._accept_ud: Optional[int] = engine.arm_accept(self)
        self.bound_port = sock.getsockname()[1]

    def _on_accept_cqe(self, ud: int, res: int, terminal: bool) -> None:
        if terminal:
            self._accept_ud = None
        if res >= 0:
            if self._closed:
                try:
                    os.close(res)
                except OSError:
                    pass
            else:
                self._accepted.append(res)
                self._wake()
        elif res not in (-_ECANCELED, -errno.ECONNABORTED,
                         -errno.EMFILE, -errno.ENFILE):
            self._accepted.append(ConnectionAbortedError(
                -res, os.strerror(-res)))
            self._wake()
        if terminal and not self._closed:
            self._accept_ud = self._engine.arm_accept(self)

    def _wake(self) -> None:
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)
        self._waiter = None

    def _engine_dead(self) -> None:
        self._accept_ud = None
        if not self._closed:
            self._accepted.append(ConnectionAbortedError(
                errno.EBADF, "uring engine closed"))
            self._wake()

    async def accept(self) -> UnfinalizedConnection:
        while not self._accepted:
            if self._closed:
                raise ConnectionAbortedError(errno.EBADF, "listener closed")
            if self._waiter is None:
                self._waiter = \
                    asyncio.get_running_loop().create_future()
            await asyncio.shield(self._waiter)
        item = self._accepted.popleft()
        if isinstance(item, BaseException):
            raise item
        sock = socket.socket(fileno=item)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            os.set_inheritable(item, False)
        except OSError:
            pass
        try:
            peer = "%s:%s" % sock.getpeername()[:2]
        except OSError:
            peer = "?"
        return _UringUnfinalized(sock, self._engine, f"tcp:{peer}")

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        eng = self._engine
        if self._accept_ud is not None and not eng.closed:
            eng.cancel_op(self._accept_ud)
        while self._accepted:
            fd = self._accepted.popleft()
            if isinstance(fd, int):
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._wake()
        try:
            self._sock.close()
        except OSError:
            pass


async def uring_connect(host: str, port: int, limiter: Limiter,
                        label: str) -> Connection:
    loop = asyncio.get_running_loop()
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setblocking(False)
        await loop.sock_connect(sock, (host, port))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except BaseException:
        sock.close()
        raise
    return Connection(UringStream(sock, UringEngine.current()), limiter,
                      label=label)


def uring_bind(host: str, port: int, reuse_port: bool = False):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.setblocking(False)
        sock.bind((host, port))
        sock.listen(512)
    except BaseException:
        sock.close()
        raise
    return UringListener(sock, UringEngine.current())
