"""Fused data-plane pump: the Python policy plane over ``native/pump.cpp``.

The native side (``pushcdn_tpu/native/pump.py`` binding) does the
per-frame work with zero Python: scan a recv chunk's frame headers in
place, plan fan-out against the live RouteTable snapshot, build per-peer
zero-copy runs over the pooled chunk buffer, and prep linked send SQEs
on the shard's io_uring.  Everything that is a *decision* stays here:

- **Engagement** — which connections get a native peer slot.  Only
  local-shard connections whose stream is a ``UringStream`` on this
  loop's engine are eligible, and a peer is engaged only at a moment of
  full Python-side idleness (empty TX deque, no in-flight chain, empty
  writer queue, writer mutex free) so the C queue can never reorder
  against bytes Python already accepted.
- **Fencing** — per-peer ordering against Python-enqueued frames.
  ``Connection._ensure_writer`` (called at every queued-send enqueue)
  and ``UringStream.write``/``writev`` fence the peer synchronously;
  while fenced the planner diverts that peer's frames to the residual
  path, which funnels through the same writer queue.  The fence lifts
  only when both sides are drained (C pending == 0 and the Python
  predicate above), swept on stream-idle transitions and at every
  plan call.
- **Lease reconciliation** — the chunk's pool permit.  When the native
  side keeps byte ranges referenced by queued/in-flight runs it takes a
  chunk slot; we park ``chunk.lease()`` under that slot and release it
  when the slot comes back on the released-slot channel (drained after
  every releasing native call, *before* any new ``route_chunk`` so a
  recycled slot can never alias a still-parked lease).
- **Escalation** — every frame the pump does not send natively is
  counted by reason (``cdn_pump_escalations``) and handed back as a
  (peer, frame) residual pair for the existing cut-through
  ``_send_plan`` path; control/traced/malformed frames stop the batch
  exactly like the plain planner.
- **Failure** — a peer whose chain errors is *disengaged only*; the
  frame flows through the Python path next, which discovers the broken
  socket itself and makes the identical disconnect decision
  ("send failed") the non-pumped path would have made.

Composition (ISSUE 15 satellite): the pump engages only when BOTH
native layers probe live — the route-plan kernel and the io_uring
engine — plus the pump library itself builds.  ``resolve_pump`` emits
one honest demotion warning naming exactly which layer failed; every
per-frame fallback after that is counted, never silent.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

import numpy as np

from pushcdn_tpu.native import pump as npump
from pushcdn_tpu.native import routeplan
from pushcdn_tpu.native import uring as nuring
from pushcdn_tpu.proto import metrics as metrics_mod

logger = logging.getLogger(__name__)

# ``PUSHCDN_PUMP``: ``auto`` (default) engages the fused pump when the
# composition probe passes; ``off`` disables it unconditionally.  There
# is deliberately no "force" value — the pump composes *on top of*
# ``--route-impl native`` + ``--io-impl uring``, and forcing it past a
# dead layer could only mislabel a bench.
PUMP_IMPL = {"0": "off", "off": "off", "false": "off", "no": "off",
             "disabled": "off"}.get(
    os.environ.get("PUSHCDN_PUMP", "auto").strip().lower(), "auto")

_warned_demote = False
_MAX_PEERS = 4096
_CHUNK_SLOTS = 64
_QUIESCE_TIMEOUT = 5.0


def configured_pump() -> str:
    return PUMP_IMPL


def set_pump_impl(value: str) -> None:
    """Test hook mirroring ``set_io_impl``."""
    global PUMP_IMPL, _warned_demote
    PUMP_IMPL = "off" if value in ("0", "off", "false", "no",
                                   "disabled") else "auto"
    _warned_demote = False


def resolve_pump(quiet: bool = False):
    """Composition probe: ``(ok, why)``.

    ``ok`` only when the route-plan kernel, the io_uring impl, and the
    pump library are ALL live.  On the first failed probe (unless
    ``quiet``) logs one demotion warning naming the dead layer — the
    r15 convention: demote loudly once, count silently after.
    """
    global _warned_demote
    if PUMP_IMPL == "off":
        return False, "disabled (PUSHCDN_PUMP=off)"
    from pushcdn_tpu.proto.transport import uring as umod
    failed = []
    if not routeplan.available():
        failed.append("route-plan kernel unavailable")
    if umod.resolve_io_impl() != "uring":
        if nuring.available():
            failed.append("io impl resolved to asyncio")
        else:
            failed.append("io_uring unavailable (%s)"
                          % nuring.probe_errname())
    if not failed and not npump.available():
        failed.append("pump library failed to build")
    if failed:
        why = "; ".join(failed)
        if not quiet and not _warned_demote:
            _warned_demote = True
            logger.warning("fused data-plane pump demoted to per-chunk "
                           "Python routing: %s", why)
        return False, why
    return True, "ok"


class PumpBinding:
    """One engaged peer: (Connection, UringStream) ↔ native peer slot."""

    __slots__ = ("state", "conn", "stream", "pid", "is_user", "key",
                 "fenced", "gate", "closed")

    def __init__(self, state: "PumpState", conn, stream, pid: int,
                 is_user: bool, key):
        self.state = state
        self.conn = conn
        self.stream = stream
        self.pid = pid
        self.is_user = is_user
        self.key = key
        self.fenced = False
        self.gate: Optional[asyncio.Future] = None
        self.closed = False

    def fence(self) -> None:
        """Synchronous — called from ``Connection._ensure_writer`` at
        enqueue time, before the event loop can run the route task, so
        the planner diverts this peer's frames to the writer queue."""
        if self.closed or self.fenced:
            return
        self.fenced = True
        st = self.state
        if not st.np_.closed:
            st.np_.set_fence(self.pid, True)
        st.fenced.add(self)

    def pending(self) -> int:
        st = self.state
        if self.closed or st.np_.closed:
            return 0
        return st.np_.peer_pending(self.pid)

    async def _await_drained(self) -> None:
        """Park until the native side has nothing queued or in flight
        for this peer (or the binding/engine dies)."""
        st = self.state
        while (not self.closed and not st.closed and not st.np_.closed
               and st.np_.peer_pending(self.pid) > 0):
            g = self.gate
            if g is None or g.done():
                g = self.gate = st.engine._loop.create_future()
                st.gated.add(self)
            await asyncio.shield(g)

    async def write_gate(self) -> None:
        """Stream-level fence: before a Python write may queue bytes on
        this fd, divert future planned frames to the writer path and
        wait out any native runs already queued — no interleave."""
        if self.closed:
            return
        self.fence()
        if self.pending() > 0:
            await self._await_drained()

    async def quiesce_and_drop(self) -> None:
        """Graceful close: let queued native runs reach the wire before
        the stream flushes/FINs, then free the peer slot."""
        try:
            await asyncio.wait_for(self._await_drained(), _QUIESCE_TIMEOUT)
        except (asyncio.TimeoutError, OSError):
            pass
        self.state.unbind(self, drop=True)

    def drop_now(self) -> None:
        """Abort path: synchronous; in-flight CQEs for this peer drain
        their buffer refs natively, the slot frees at quiesce."""
        self.state.unbind(self, drop=True)


class PumpState:
    """Per-engine pump: native handle + engagement/fence/lease policy.

    ONE per ``UringEngine`` (i.e. per event loop), claimed by the first
    RouteState that asks; a second broker sharing the loop keeps plain
    cut-through (honest limitation — peer slots key on fd, and two
    brokers' route tables can't share one slot map).
    """

    __slots__ = ("engine", "broker", "np_", "owner", "bindings", "by_pid",
                 "pending_engage", "leases", "fenced", "gated",
                 "slots_version", "slots_dirty", "closed",
                 "escalations", "pump_calls", "pump_frames",
                 "python_chunks", "_esc_cache")

    def __init__(self, engine, broker, native: "npump.NativePump"):
        self.engine = engine
        self.broker = broker
        self.np_ = native
        self.owner = None
        self.bindings: dict = {}        # stream -> PumpBinding
        self.by_pid: dict = {}          # pid -> PumpBinding
        self.pending_engage: dict = {}  # stream -> (conn, is_user, key)
        self.leases: dict = {}          # chunk_slot -> (BytesLease, buf)
        self.fenced: set = set()
        self.gated: set = set()
        self.slots_version = -2         # rs.version never starts at -2
        self.slots_dirty = True
        self.closed = False
        self.escalations: dict = {}     # reason -> count (summary mirror)
        self.pump_calls = 0             # route_chunk calls with >=1 pumped pair
        self.pump_frames = 0
        self.python_chunks = 0          # calls where everything escalated
        self._esc_cache: dict = {}

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, engine, broker, owner) -> Optional["PumpState"]:
        existing = getattr(engine, "pump_state", None)
        if existing is not None and not existing.closed:
            return existing if existing.owner is owner else None
        native = npump.NativePump.create(
            engine.ring, max_peers=_MAX_PEERS, chunk_slots=_CHUNK_SLOTS)
        if native is None:
            return None
        ps = cls(engine, broker, native)
        ps.owner = owner
        engine.pump_state = ps
        return ps

    def engine_dead(self) -> None:
        """Engine teardown: destroy the native pump BEFORE the ring
        closes (the pump preps SQEs on the ring's memory), drop every
        parked lease, and wake any gated writers."""
        if self.closed:
            return
        self.closed = True
        for b in list(self.bindings.values()):
            b.closed = True
            g = b.gate
            if g is not None and not g.done():
                g.set_result(None)
            if b.stream._pump_binding is b:
                b.stream._pump_binding = None
                b.stream._pump_state = None
        self.bindings.clear()
        self.by_pid.clear()
        self.fenced.clear()
        self.gated.clear()
        for stream in self.pending_engage:
            stream._pump_state = None
        self.pending_engage.clear()
        self.leases.clear()
        self.np_.destroy()
        if getattr(self.engine, "pump_state", None) is self:
            self.engine.pump_state = None

    # -- escalation accounting ----------------------------------------------

    def _esc(self, reason: str, n: int = 1) -> None:
        if n <= 0:
            return
        c = self._esc_cache.get(reason)
        if c is None:
            c = metrics_mod.PUMP_ESCALATIONS.labels(reason=reason)
            self._esc_cache[reason] = c
        c.inc(n)
        self.escalations[reason] = self.escalations.get(reason, 0) + n

    # -- engagement ----------------------------------------------------------

    @staticmethod
    def _python_idle(stream, conn) -> bool:
        """No byte Python has accepted may still be waiting: TX deque
        empty, no chain in flight, writer queue empty, mutex free."""
        return (not stream._tx and stream._tx_flight == 0
                and stream._tx_err is None and not stream._closed
                and conn._send_q.empty()
                and not conn._write_mutex.locked())

    def request_engage(self, stream, conn, is_user: bool, key) -> None:
        if (self.closed or stream in self.bindings
                or stream in self.pending_engage):
            return
        self.pending_engage[stream] = (conn, is_user, key)
        stream._pump_state = self
        if self._python_idle(stream, conn):
            self._try_engage(stream)

    def _try_engage(self, stream) -> None:
        info = self.pending_engage.get(stream)
        if info is None or self.closed or self.np_.closed:
            return
        conn, is_user, key = info
        if not self._python_idle(stream, conn):
            return  # retried at the next stream-idle transition
        del self.pending_engage[stream]
        pid = self.np_.add_peer(stream._fd)
        if pid < 0:
            self._esc("capacity")
            stream._pump_state = None
            return
        b = PumpBinding(self, conn, stream, pid, is_user, key)
        self.bindings[stream] = b
        self.by_pid[pid] = b
        stream._pump_binding = b
        self.slots_dirty = True

    def on_stream_idle(self, stream) -> None:
        """Hook from ``UringStream._on_send_cqe`` at TX-idle: the only
        moment engagement/unfencing is both safe and cheap to check."""
        if self.closed:
            return
        if stream in self.pending_engage:
            self._try_engage(stream)
            return
        b = stream._pump_binding
        if b is not None and b.fenced and not b.closed:
            self._maybe_unfence(b)

    def _maybe_unfence(self, b: PumpBinding) -> None:
        if (self._python_idle(b.stream, b.conn)
                and not self.np_.closed
                and self.np_.peer_pending(b.pid) == 0):
            b.fenced = False
            self.np_.set_fence(b.pid, False)
            self.fenced.discard(b)

    def _sweep_unfence(self) -> None:
        for b in list(self.fenced):
            if b.closed:
                self.fenced.discard(b)
            else:
                self._maybe_unfence(b)

    def unbind(self, b: PumpBinding, drop: bool) -> None:
        if b.closed:
            return
        b.closed = True
        self.bindings.pop(b.stream, None)
        self.by_pid.pop(b.pid, None)
        self.fenced.discard(b)
        self.gated.discard(b)
        if b.stream._pump_binding is b:
            b.stream._pump_binding = None
            b.stream._pump_state = None
        self.slots_dirty = True
        g = b.gate
        if g is not None and not g.done():
            g.set_result(None)
        if drop and not self.np_.closed:
            self.np_.drop_peer(b.pid)
            self._release_slots(self.np_.take_released())

    def _peer_errored(self, b: PumpBinding, err: int) -> None:
        """Deferred (call_soon) from the drain loop.  Disengage ONLY —
        the Python send path rediscovers the broken socket and makes
        the byte-identical disconnect decision the non-pumped path
        would have made."""
        if self.closed or b.closed:
            return
        self._esc("peer_error_event")
        self.unbind(b, drop=True)

    # -- slot map ------------------------------------------------------------

    def _resync(self, rs) -> None:
        """Rebuild the native slot→peer map against the CURRENT
        snapshot: O(engaged peers), revalidating each binding's
        identity against live Connections state (a slot recycled to a
        different user must never inherit the old user's fd)."""
        conns = self.broker.connections
        local = conns.shard_id
        m = np.full(rs.user_cap + rs.broker_cap, -1, np.int32)
        for b in self.bindings.values():
            if b.closed:
                continue
            if b.is_user:
                slot = rs.user_slot.get(b.key)
                if (slot is None or rs.user_shard[slot] != local
                        or conns.get_user_connection(b.key) is not b.conn):
                    continue
            else:
                bslot = rs.broker_slot.get(b.key)
                if (bslot is None or rs.broker_shard[bslot] is not None
                        or conns.get_broker_connection(b.key) is not b.conn):
                    continue
                slot = rs.user_cap + bslot
            m[slot] = b.pid
        self.np_.set_slots(m)
        self.slots_version = rs.version
        self.slots_dirty = False

    def _request_engagements(self, rs, resid_peers) -> None:
        """Residual-unmapped peers are the engagement demand signal:
        resolve each against live Connections and register eligible
        ones (engaged at their next idle transition)."""
        conns = self.broker.connections
        local = conns.shard_id
        engine = self.engine
        for peer in np.unique(resid_peers).tolist():
            if peer < rs.user_cap:
                key = rs.slot_user[peer]
                if key is None or rs.user_shard[peer] != local:
                    continue
                conn = conns.get_user_connection(key)
                is_user = True
            else:
                bslot = peer - rs.user_cap
                ident = rs.slot_broker[bslot]
                if ident is None or rs.broker_shard[bslot] is not None:
                    continue
                conn = conns.get_broker_connection(ident)
                key = ident
                is_user = False
            if conn is None:
                continue
            stream = conn._stream
            if (getattr(stream, "_engine", None) is not engine
                    or stream._closed):
                continue  # asyncio transport / foreign loop: never pumped
            self.request_engage(stream, conn, is_user, key)

    # -- leases --------------------------------------------------------------

    def _release_slots(self, slots) -> None:
        for s in slots:
            self.leases.pop(s, None)  # dropping the lease releases it

    # -- the hot path --------------------------------------------------------

    def plan_and_pump(self, rs, chunk, buf, offs, lens, pos: int,
                      mode: int):
        """Plan + natively send one batch.  Returns ``(consumed, stop,
        resid_peers, resid_frames, pumped_pairs)`` — residual pairs go
        through the caller's existing ``_send_plan``; ``pumped_pairs``
        splits the frame attribution between path=pump and
        path=cutthrough."""
        np_ = self.np_
        # released-slot channel FIRST: a recycled chunk slot must not
        # alias a lease still parked from its previous life
        self._release_slots(np_.take_released())
        if self.fenced:
            self._sweep_unfence()
        if self.slots_dirty or self.slots_version != rs.version:
            self._resync(rs)
        consumed, stop, resid_peers, resid_frames, meta = np_.route_chunk(
            rs.planner._handle, buf, offs, lens, pos, mode)
        slot = int(meta[npump.META_CHUNK_SLOT])
        if slot >= 0:
            # native runs reference the chunk buffer: park the pool
            # lease until the slot's refcount drains to zero
            self.leases[slot] = (chunk.lease(), buf)
        if meta[npump.META_SQES] > 0:
            eng = self.engine
            eng._need_submit = True
            eng._schedule_kick()
        pumped = int(meta[npump.META_PAIRS])
        if pumped:
            self.pump_calls += 1
            self.pump_frames += pumped
            u = int(meta[npump.META_USER_PAIRS])
            if u:
                metrics_mod.EGRESS_FRAMES_USER.inc(u)
            if pumped - u:
                metrics_mod.EGRESS_FRAMES_BROKER.inc(pumped - u)
        elif consumed:
            self.python_chunks += 1
        self._esc("unengaged", int(meta[npump.META_RESID_UNMAPPED]))
        self._esc("fenced", int(meta[npump.META_RESID_FENCED]))
        self._esc("peer_error", int(meta[npump.META_RESID_ERROR]))
        self._esc("chunk_slots", int(meta[npump.META_NO_CHUNK_SLOT]))
        if stop == routeplan.STOP_RESIDUAL:
            self._esc("control")
        if len(resid_peers) and meta[npump.META_RESID_UNMAPPED]:
            self._request_engagements(rs, resid_peers)
        return consumed, stop, resid_peers, resid_frames, pumped

    # -- completion plane ----------------------------------------------------

    def _poll_gates(self) -> None:
        np_ = self.np_
        for b in list(self.gated):
            if b.closed or np_.closed or np_.peer_pending(b.pid) == 0:
                g = b.gate
                if g is not None and not g.done():
                    g.set_result(None)
                self.gated.discard(b)

    def drain(self) -> None:
        """The engine's CQ drain when a pump is live: native code walks
        the CQ, consumes pump-tagged CQEs (advancing chains, prepping
        starved ones), and hands everything else back for the normal
        Python dispatch."""
        eng = self.engine
        np_ = self.np_
        while True:
            if np_.closed or self.closed:
                return
            cqes, events, n_prepped = np_.drain()
            if n_prepped:
                eng._need_submit = True
            self._release_slots(np_.take_released())
            if events:
                loop = eng._loop
                for etype, pid, arg in events:
                    if etype == npump.EV_PEER_ERROR:
                        b = self.by_pid.get(pid)
                        if b is not None and not b.closed:
                            loop.call_soon(self._peer_errored, b, arg)
            if self.gated:
                # gates resolve by polling, not by trusting the event
                # channel (it is bounded and may have dropped an IDLE)
                self._poll_gates()
            if cqes:
                eng.cqes += len(cqes)
                complete = eng._complete
                for ud, res, flags in cqes:
                    complete(ud, res, flags)
                    if eng.closed or self.closed:
                        return
            if not cqes and not events:
                return

    # -- observability -------------------------------------------------------

    def summary(self) -> dict:
        native = None if self.np_.closed else self.np_.stats()
        return {
            "engaged_peers": len(self.bindings),
            "fenced_peers": len(self.fenced),
            "pending_engage": len(self.pending_engage),
            "parked_leases": len(self.leases),
            "slots_version": self.slots_version,
            "pump_calls": self.pump_calls,
            "pump_frames": self.pump_frames,
            "all_residual_chunks": self.python_chunks,
            "escalations": dict(self.escalations),
            "native": native,
        }
