"""The transport-generic connection machinery.

Capability parity with cdn-proto/src/connection/protocols/mod.rs:

- ``Protocol`` — connect/bind with associated listener + unfinalized
  connection types (mod.rs:40-81).
- ``Connection`` — the uniform handle: two actor tasks (writer-drain and
  reader-pump) bridged to callers by queues (mod.rs:139-217), with
  ``send_message[_raw]`` / ``recv_message[_raw]`` / ``soft_close``
  (mod.rs:223-306).
- Length-delimited framing: u32 big-endian length prefix then payload, max
  ``MAX_MESSAGE_SIZE``, 5 s per-frame read/write timeouts
  (mod.rs:309-394; cdn-proto/src/lib.rs:25).
- Backpressure lands on the socket, not the router (mod.rs:328): frames
  larger than the read chunk acquire their limiter byte-permit before the
  payload is buffered; small frames parsed out of an already-read chunk
  acquire theirs before entering the receive queue, so the unpermitted
  overshoot is bounded by ``Connection._READ_CHUNK`` per connection and
  a blocked permit still stops further socket reads.
"""

from __future__ import annotations

import abc
import asyncio
import struct
import time
import weakref
from collections import deque
from typing import List, Optional

from pushcdn_tpu import native
from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Bytes, Limiter, NO_LIMIT
from pushcdn_tpu.proto.message import (
    Message,
    decode_frames,
    deserialize,
    deserialize_owned,
    materialize,
    serialize,
)
from pushcdn_tpu.proto import flightrec
from pushcdn_tpu.proto import ledger as ledger_mod
from pushcdn_tpu.proto import metrics as metrics_mod

# Live connections (weak), for the metrics writer-queue-depth pre-render
# hook and /debug introspection.
LIVE_CONNECTIONS: "weakref.WeakSet[Connection]" = weakref.WeakSet()

# Parity: 5 s read/write timeouts (protocols/mod.rs:336, :368, :379) and a
# 5 s connect timeout (tcp.rs).
WRITE_TIMEOUT_S = 5.0
READ_TIMEOUT_S = 5.0
CONNECT_TIMEOUT_S = 5.0

_LEN = struct.Struct(">I")

_CLOSE = object()  # sentinel queued to ask the writer task to soft-close


class FrameChunk:
    """A run of complete frames parsed from ONE read chunk, sharing one
    detached buffer and one pool permit — the receive-side twin of the
    egress engine's per-user streams. The reader enqueues one of these per
    parse batch instead of per-frame :class:`Bytes`, so a 250-frame chunk
    costs one buffer copy and one queue put, not 250 of each.

    Consumption modes:
    - :meth:`take` materializes the next frame as a permit-sharing
      :class:`Bytes` (compat path for ``recv_raw``/``recv_raw_many``);
    - :meth:`views` hands out zero-copy memoryviews of every remaining
      frame for whole-chunk consumers (``Client.receive_messages``), who
      call :meth:`release` when done.

    Pool accounting is deliberately chunk-granular: ONE permit covers the
    whole batch, and a consumer retaining any single taken frame pins it
    until that frame is released too. The coarser unit trades worst-case
    precision (bounded by one read chunk per long-held frame) for not
    paying a permit per frame; under pool pressure the reader falls back
    to exact per-frame permits (see ``_reader_loop``).
    """

    __slots__ = ("buf", "offs", "lens", "_pos", "_master")

    def __init__(self, buf: bytes, offs, lens, permit):
        self.buf = buf
        self.offs = offs
        self.lens = lens
        self._pos = 0
        self._master = Bytes(buf, permit)

    @property
    def remaining(self) -> int:
        return len(self.offs) - self._pos

    def take(self) -> Bytes:
        """Materialize the next frame (shares the chunk's permit via the
        Bytes refcount: the permit frees when the chunk AND every taken
        frame are released)."""
        i = self._pos
        self._pos = i + 1
        o = self.offs[i]
        b = self._master.clone()
        b.data = self.buf[o:o + self.lens[i]]
        if self._pos == len(self.offs):
            self._master.release()  # fully handed out
        return b

    def views(self):
        """Zero-copy memoryviews of every remaining frame; the caller owns
        consumption and MUST call :meth:`release` afterwards."""
        mv = memoryview(self.buf)
        return [mv[self.offs[i]:self.offs[i] + self.lens[i]]
                for i in range(self._pos, len(self.offs))]

    def decode_remaining(self, zero_copy: bool = True) -> list:
        """Decode every remaining frame into Message objects (the batch
        decoder runs straight over the shared buffer) and release the
        chunk. The fan-out consumer's one-call drain.

        By default Broadcast/Direct payloads of at least
        ``message.ZERO_COPY_MIN`` bytes are ZERO-COPY memoryviews of the
        chunk buffer (``message.decode_frames`` zero_copy docs): the
        views keep the buffer alive after the release below, so the last
        per-message copy on the client receive path is gone for the
        payload sizes where it costs anything; smaller payloads stay
        owned copies (bounds how much chunk memory retained messages can
        pin after the pool permit returns). Pass ``zero_copy=False`` for
        owned bytes payloads throughout."""
        try:
            return decode_frames(self.buf, self.offs, self.lens, self._pos,
                                 zero_copy=zero_copy)
        finally:
            self.release()

    def lease(self):
        """A :class:`pushcdn_tpu.proto.limiter.BytesLease` over the
        chunk's master reference: keeps the buffer + pool permit alive
        until the lease is dropped. The cut-through routing plane attaches
        one to each writer entry that flushes a zero-copy view of this
        chunk, so ``release()``-ing the chunk after planning cannot free
        the permit under a pending flush."""
        from pushcdn_tpu.proto.limiter import BytesLease
        return BytesLease(self._master)

    def release(self) -> None:
        """Drop the untaken remainder (idempotent)."""
        if self._pos < len(self.offs):
            self._pos = len(self.offs)
            self._master.release()


class PreEncoded:
    """An already-length-delimited byte stream: the writer sends it
    verbatim, adding no framing. This is the egress batch handoff — the
    native engine (native.egress_encode) encodes a whole step's worth of
    frames for one user into one buffer, the routing loops pre-encode
    per-peer fan-out batches (FrameEncoder.encode_detached), and the
    connection flushes either with one write instead of re-framing per
    message. ``owner`` is an opaque keep-alive (e.g. the EgressStreams
    whose pooled buffer ``data`` views): it rides the queue entry until
    the flush completes, so buffer recycling can never race a pending
    write."""

    __slots__ = ("data", "owner")

    def __init__(self, data, owner=None):
        self.data = data  # bytes / memoryview over the step's egress buffer
        self.owner = owner


def _py_scan_frames(buf, max_frame_len: int):
    """Python fallback for native.FrameScanner.scan: walk a carry buffer
    for complete length-delimited frames. Returns (payload_offsets,
    payload_lengths, consumed, oversized_error)."""
    offs: list = []
    lens: list = []
    pos = 0
    blen = len(buf)
    error = False
    while blen - pos >= 4:
        (length,) = _LEN.unpack_from(buf, pos)
        if length > max_frame_len:
            error = True
            break
        if blen - pos - 4 < length:
            break
        offs.append(pos + 4)
        lens.append(length)
        pos += 4 + length
    return offs, lens, pos, error


class RawStream(abc.ABC):
    """Minimal async byte-stream pair every transport lowers to."""

    # streams that set this accept ``write(data, owner)`` /
    # ``writev(bufs, owner)`` and anchor the owner lease until the
    # kernel is done with the bytes (io_uring zero-copy deferral)
    wants_owner = False

    @abc.abstractmethod
    async def read_exactly(self, n: int) -> bytes: ...

    async def read_some(self, max_n: int) -> bytes:
        """Return at least 1 and at most ``max_n`` bytes; raise
        ``IncompleteReadError`` at EOF. Transports override this with a
        real bulk read — the reader loop uses it to parse many small
        frames per wakeup instead of two awaits per frame."""
        return await self.read_exactly(1)

    @abc.abstractmethod
    async def write(self, data) -> None:
        """Buffer ``data`` and flush (may await backpressure)."""

    async def writev(self, bufs) -> None:
        """Vectored write: flush ``bufs`` back-to-back as one unit.
        Transports with a gather-capable sink override this (asyncio's
        ``writelines`` hands the whole run to one transport write); the
        default is sequential — correctness-equivalent, one flush per
        buffer."""
        for b in bufs:
            await self.write(b)

    @abc.abstractmethod
    async def close(self) -> None:
        """Flush and close the write side gracefully."""

    @abc.abstractmethod
    def abort(self) -> None:
        """Tear down immediately."""


class AsyncioStream(RawStream):
    """RawStream over an asyncio (StreamReader, StreamWriter) pair."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def read_exactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def read_some(self, max_n: int) -> bytes:
        data = await self.reader.read(max_n)
        if not data:
            raise asyncio.IncompleteReadError(b"", 1)
        return data

    async def write(self, data) -> None:
        # memoryviews are materialized here (not passed through): newer
        # asyncio transports keep buffer references instead of copying,
        # and the egress pool recycles the underlying buffer as soon as
        # its lease drops — the transport must own a private copy
        self.writer.write(bytes(data) if isinstance(data, memoryview) else data)
        await self.writer.drain()

    async def writev(self, bufs) -> None:
        # one gather handoff: writelines joins the run into a single
        # transport write (one kernel handoff instead of one per buffer)
        self.writer.writelines(
            [bytes(b) if isinstance(b, memoryview) else b for b in bufs])
        await self.writer.drain()

    async def close(self) -> None:
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass

    def abort(self) -> None:
        try:
            self.writer.transport.abort()
        except Exception:
            try:
                self.writer.close()
            except Exception:
                pass


class Connection:
    """Uniform connection handle with actor-style reader/writer tasks.

    Shape parity with protocols/mod.rs:139-217: a writer task drains a send
    queue into the stream; a reader task pumps length-delimited frames into
    a receive queue (acquiring limiter permits first). Any I/O error poisons
    the connection: both queues wake with the error and subsequent calls
    raise ``Error(CONNECTION)`` — the caller's policy is removal/reconnect
    (fault detection *is* "send failed", tasks/broker/sender.rs:35-43).
    """

    def __init__(self, stream: RawStream, limiter: Limiter = NO_LIMIT,
                 label: str = "?"):
        self._stream = stream
        self._limiter = limiter
        self.label = label
        # owner-aware streams (io_uring) take the PreEncoded lease down
        # the flush path so zero-copy sends can defer its release until
        # the kernel's completion notification
        self._owner_write = bool(getattr(stream, "wants_owner", False))
        # per-transport byte accounting: the label's prefix is the
        # transport name ("tcp:host:port" → "tcp"); the labeled children
        # are cached here so the hot path pays one plain inc per flush
        transport = label.split(":", 1)[0] or "?"
        self._m_sent = metrics_mod.BYTES_SENT.labels(transport=transport)
        self._m_recv = metrics_mod.BYTES_RECV.labels(transport=transport)
        # flight recorder: the last ~64 structured events on this
        # connection, dumped to the diagnostics log on abnormal death and
        # readable at /debug/flightrec
        self.flightrec = flightrec.FlightRecorder(label)
        self.flightrec.record("connect")
        # frame-fate ledger attribution (ISSUE 20): broker links carry
        # their peer identifier so dequeues count as relayed{peer} in the
        # per-link conservation tables; teardown drains attribute their
        # dropped frames to this reason (send_failed / parting_expiry)
        # instead of the generic writer_teardown
        self.ledger_peer: Optional[str] = None
        self.ledger_drop_reason: Optional[str] = None
        LIVE_CONNECTIONS.add(self)
        qsize = limiter.queue_size()
        self._send_q: asyncio.Queue = asyncio.Queue(maxsize=qsize)
        self._recv_q: asyncio.Queue = asyncio.Queue(maxsize=qsize)
        # frames already popped off _recv_q but not yet handed to a caller
        # (the reader enqueues whole parse batches; receivers drain here)
        self._recv_pending: deque = deque()
        self._error: Optional[Error] = None
        self._closed = False
        # serializes stream writes between the writer task and the inline
        # flush fast path in send_raw (see there)
        self._write_mutex = asyncio.Lock()
        # the writer task spawns lazily on the first QUEUED send: a
        # handshake-only link whose few flushed sends all take the inline
        # fast path never pays the task spawn (or its batch encoder)
        self._writer_task: Optional[asyncio.Task] = None
        # True while the writer is in the load regime (last wakeup flushed
        # a multi-frame batch) — gates the adaptive coalesce window
        self._coalescing = False
        self._reader_task = asyncio.create_task(self._reader_loop())
        # Permit-leak backstop (ADVICE r5): a poisoned connection keeps
        # its receive side deliverable (data-before-FIN), so _poison must
        # NOT drain it — but an ABANDONED handle (handler crash, dropped
        # reference, never close()d) would then pin its queued frames'
        # pool permits forever. The finalizer drains whatever still sits
        # in the queues when the LAST reference to this connection drops;
        # anything a consumer already took out is the consumer's to
        # release, exactly as before.
        self._finalizer = weakref.finalize(
            self, Connection._drain_abandoned,
            self._send_q, self._recv_q, self._recv_pending)

    @staticmethod
    def _drain_abandoned(send_q: asyncio.Queue, recv_q: asyncio.Queue,
                         recv_pending: deque) -> None:
        """Release every queued frame's pool permit (GC-time backstop; the
        containers are empty when ``close()`` already ran)."""
        for q in (send_q, recv_q):
            while True:
                try:
                    item = q.get_nowait()
                except (asyncio.QueueEmpty, RuntimeError):
                    break
                if item is _CLOSE or isinstance(item, Error):
                    continue
                if isinstance(item, tuple):  # entry: (payload, done, stamp)
                    stamp = item[2] if len(item) > 2 else None
                    if stamp is not None and stamp[4]:
                        ledger_mod.record_fate("dropped", "writer_teardown",
                                               stamp[1], stamp[4])
                    item = item[0]
                    if type(item) is PreEncoded:
                        continue
                if isinstance(item, (Bytes, FrameChunk)):
                    item.release()
                elif isinstance(item, list):
                    for p in item:
                        if isinstance(p, Bytes):
                            p.release()
        while recv_pending:
            item = recv_pending.popleft()
            if isinstance(item, (Bytes, FrameChunk)):
                item.release()

    def _ensure_writer(self) -> None:
        if self._writer_task is None:
            self._writer_task = asyncio.create_task(self._writer_loop())
        # fused-pump fence (transport/pump.py): a frame just entered the
        # Python writer queue, so until it drains this peer's planned
        # frames must route through the queue too — fencing here is
        # SYNCHRONOUS with the enqueue, before the route task can plan
        b = getattr(self._stream, "_pump_binding", None)
        if b is not None:
            b.fence()

    def queue_stats(self) -> tuple:
        """``(entries, bytes)`` waiting in the send queue — the topology
        endpoint's per-peer backpressure view. Event-loop context only:
        peeks the queue's internal deque without mutating it (an entry
        dequeued concurrently just stops being counted)."""
        depth = self._send_q.qsize()
        total = 0
        try:
            for item in list(self._send_q._queue):
                if isinstance(item, tuple):
                    item = item[0]
                if isinstance(item, list):
                    for p in item:
                        data = p.data if isinstance(p, Bytes) else p
                        total += len(data)
                elif isinstance(item, (Bytes, PreEncoded)):
                    total += len(item.data)
                elif isinstance(item, (bytes, bytearray, memoryview)):
                    total += len(item)
        except Exception:
            pass
        return depth, total

    # -- actor loops --------------------------------------------------------

    # Batch small frames into one buffer per flush: per-frame event-loop +
    # syscall overhead dominates ≤1 KB frames otherwise (BASELINE.md soft
    # spot). Each flush unit stays under this size so the per-flush 5 s
    # timeout keeps the same granularity the old per-frame timeout had;
    # frames above the limit are written directly, no extra copy.
    _BATCH_COALESCE_LIMIT = 64 * 1024

    async def _flush(self, buf, owner=None) -> None:
        """One bounded write under its own timeout; BYTES_SENT counts only
        bytes that actually flushed."""
        async with asyncio.timeout(WRITE_TIMEOUT_S):
            if owner is not None and self._owner_write:
                await self._stream.write(buf, owner)
            else:
                await self._stream.write(buf)
        self._m_sent.inc(len(buf))

    async def _flush_v(self, bufs, owner=None) -> None:
        """Vectored twin of :meth:`_flush`: one timeout window, one gather
        handoff (``writev``) for a run of buffers."""
        async with asyncio.timeout(WRITE_TIMEOUT_S):
            if owner is not None and self._owner_write:
                await self._stream.writev(bufs, owner)
            else:
                await self._stream.writev(bufs)
        self._m_sent.inc(sum(len(b) for b in bufs))

    # an owner-aware stream (io_uring) turns a chunked PreEncoded flush
    # into linked-SQE chains: up to this many chunks per submission share
    # one timeout window and one kernel handoff
    _CHAIN_GROUP = 16

    async def _flush_chunked(self, data, owner=None) -> None:
        """Flush an already-framed stream (PreEncoded) in bounded chunks so
        slow links get one timeout window per chunk, not one for the lot."""
        n = len(data)
        chunk = 4 * self._BATCH_COALESCE_LIMIT
        if n <= chunk:
            await self._flush(data, owner)
            return
        view = memoryview(data)
        if self._owner_write:
            group = self._CHAIN_GROUP * chunk
            for base in range(0, n, group):
                top = min(n, base + group)
                await self._flush_v(
                    [view[off:off + chunk]
                     for off in range(base, top, chunk)], owner)
            return
        for off in range(0, n, chunk):
            await self._flush(view[off:off + chunk])

    async def _writer_loop(self) -> None:
        # the native batch encoder length-delimits a run of small frames in
        # one C call + one copy; created lazily on the first BATCH (its
        # reusable output buffer is a ~256 KiB allocation that depth-1 and
        # handshake traffic never needs). None ⇒ Python coalescer.
        encoder_cell = [False]  # False = not created yet; None = no native
        enc_cap = 3 * self._BATCH_COALESCE_LIMIT
        batch: list = []
        try:
            while True:
                item = await self._send_q.get()
                # every write section holds the mutex: send_raw's inline
                # flush fast path writes from the sender's task, and the
                # two paths must never interleave bytes on the stream.
                # The mutex is taken BEFORE the adaptive yield below: a
                # dequeued-but-unwritten entry with the mutex free would
                # let a concurrent inline flush write a NEWER frame first
                # (wire reorder); holding it keeps the inline path out
                # while producers (who only need the queue) still fill
                # the coalesce window during the yield.
                await self._write_mutex.acquire()
                try:
                    # Adaptive coalesce window: when the PREVIOUS wakeup
                    # coalesced (load regime) and this one would flush a
                    # lone frame, yield one loop tick first — ready
                    # producer tasks enqueue their frames and this flush
                    # carries a batch too. An idle link (previous flush
                    # was depth-1) writes immediately: the latency regime
                    # never waits.
                    if self._coalescing and self._send_q.empty():
                        try:
                            await asyncio.sleep(0)
                        except asyncio.CancelledError:
                            # cancelled in the yield: the dequeued entry
                            # is in neither the queue nor `batch` — its
                            # permits and flush future are ours to settle
                            if item is not _CLOSE:
                                self._account_dropped(item, None)
                                payload, done = item[0], item[1]
                                if type(payload) is list:
                                    for p in payload:
                                        if isinstance(p, Bytes):
                                            p.release()
                                elif isinstance(payload, Bytes):
                                    payload.release()
                                if done is not None and not done.done():
                                    done.cancel()
                            raise
                    closed = await self._writer_item(item, encoder_cell,
                                                     enc_cap, batch)
                finally:
                    self._write_mutex.release()
                # Drop the entry reference BEFORE parking on the queue: a
                # flushed entry's ``owner`` keep-alive (egress-buffer
                # lease, cut-through chunk permit lease) must release when
                # the flush completes, not when the NEXT send arrives on
                # an idle link.
                item = None
                if closed:
                    return
        except asyncio.CancelledError:
            # close() cancels the writer mid-flush: flush=True senders whose
            # entries were already dequeued are beyond _drain_queues' reach
            # and must not await forever (matches the drain's err=None
            # cancel semantics)
            for entry in batch:
                if entry is not _CLOSE and entry[1] is not None \
                        and not entry[1].done():
                    entry[1].cancel()
            raise
        except Exception as exc:
            err = Error(ErrorKind.CONNECTION, f"write failed: {exc!r}", exc)
            # flush=True senders whose entries we already dequeued must see
            # the failure (they are beyond _poison's queue drain)
            for entry in batch:
                if entry is not _CLOSE and entry[1] is not None \
                        and not entry[1].done():
                    entry[1].set_exception(err)
            self._poison(err)

    def _account_entry(self, entry, now: float) -> None:
        """Per-class flow accounting at dequeue: the entry's enqueue stamp
        is ``(t_enq, class, frames, bytes, real_frames)`` — observe the
        writer-queue delay for its class and fold the frame/byte counts
        into the egress class counters. ``frames``/``bytes`` may be 0
        when the caller pre-counted the volume at the routing decision;
        ``real_frames`` always carries the entry's actual frame count so
        the conservation ledger stays exact either way. Accounts entries
        dequeued FOR writing (a flush that subsequently fails is still
        counted here; ``BYTES_SENT`` remains the flushed-bytes ground
        truth, and the mesh audit's link deficit catches wire loss)."""
        stamp = entry[2]
        if stamp is None:
            return
        metrics_mod.WRITER_QUEUE_DELAY_CLS[stamp[1]].observe(now - stamp[0])
        if stamp[2]:
            metrics_mod.CLASS_FRAMES_OUT[stamp[1]].inc(stamp[2])
        if stamp[3]:
            metrics_mod.CLASS_BYTES_OUT[stamp[1]].inc(stamp[3])
        ledger_mod.on_dequeued(stamp[1], stamp[4], self.ledger_peer)

    def _account_dropped(self, item, err: Optional[Error]) -> None:
        """Fate accounting for one drained (never-written) send-queue
        entry."""
        stamp = item[2] if type(item) is tuple and len(item) > 2 else None
        if stamp is None or not stamp[4]:
            return
        reason = self.ledger_drop_reason or (
            "conn_poisoned" if err is not None else "writer_teardown")
        ledger_mod.record_fate("dropped", reason, stamp[1], stamp[4])

    async def _writer_item(self, item, encoder_cell, enc_cap,
                           batch: list) -> bool:
        """Process one dequeued writer entry (and any batchable run behind
        it). ``batch`` is the caller's scratch list, mutated IN PLACE —
        in-flight entries live there so the writer loop's cancel/error
        handlers can resolve their futures. Always called under
        ``_write_mutex`` (the inline flush path in ``send_raw`` takes the
        same mutex)."""
        if item is _CLOSE:
            await self._stream.close()
            return True
        # one clock read per wakeup covers every entry this drain accounts
        now = time.monotonic()
        self._account_entry(item, now)
        # Depth-1 fast path (the latency regime): one small single frame
        # and nothing else queued — write it directly, skipping batch
        # assembly, the get_nowait exception, flattening and encoder
        # probing. This is what a handshake or an idle-link echo pays per
        # message.
        if self._send_q.empty():
            payload, done = item[0], item[1]
            if type(payload) is PreEncoded:
                # a PreEncoded entry IS a fan-out batch (routing-loop /
                # device-plane egress): it counts as the load regime, so
                # the adaptive window arms for the next wakeup. The entry
                # rides `batch` during the flush so a timeout/cancel
                # mid-write settles its flush future via the loop's
                # handlers (same pattern as the small-frame path below).
                self._coalescing = True
                batch.append(item)
                await self._flush_chunked(payload.data, payload.owner)
                batch.clear()
                if done is not None and not done.done():
                    done.set_result(None)
                return False
            self._coalescing = False
            if type(payload) is not list:
                data = payload.data if isinstance(payload, Bytes) \
                    else payload
                n = len(data)
                if n <= self._BATCH_COALESCE_LIMIT:
                    batch.append(item)
                    try:
                        one = bytearray(_LEN.pack(n))
                        one += data
                        await self._flush(one)
                    finally:
                        if isinstance(payload, Bytes):
                            payload.release()
                    batch.clear()
                    if done is not None and not done.done():
                        done.set_result(None)
                    return False
        # Drain everything queued right now into one write batch.
        batch.append(item)
        while len(batch) < 512:
            try:
                nxt = self._send_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            batch.append(nxt)
            if nxt is _CLOSE:
                break
            self._account_entry(nxt, now)

        if encoder_cell[0] is False:
            encoder_cell[0] = native.FrameEncoder.create(
                4 * self._BATCH_COALESCE_LIMIT)
        encoder = encoder_cell[0]
        dones = []
        close_after = False
        try:
            # flatten: an entry's payload is one frame or a whole
            # list of frames (send_raw_many batches)
            frames: list = []
            for entry in batch:
                if entry is _CLOSE:
                    close_after = True
                    break
                payload, done = entry[0], entry[1]
                if type(payload) is list:
                    for p in payload:
                        frames.append(
                            p.data if isinstance(p, Bytes) else p)
                else:
                    frames.append(payload.data
                                  if isinstance(payload, Bytes)
                                  else payload)
                if done is not None:
                    dones.append(done)
            # load-regime signal for the adaptive coalesce window: a
            # multi-entry drain OR one entry carrying a whole fan-out
            # batch (a send_raw_many list or a PreEncoded stream) both
            # mean traffic is flowing
            self._coalescing = (len(batch) > 1 or len(frames) > 1
                                or (len(frames) == 1
                                    and type(frames[0]) is PreEncoded))

            buf = bytearray()
            i, nf = 0, len(frames)
            while i < nf:
                data = frames[i]
                if type(data) is PreEncoded:
                    if buf:
                        await self._flush(buf)
                        buf = bytearray()
                    await self._flush_chunked(data.data, data.owner)
                    i += 1
                    continue
                n = len(data)
                if encoder is not None and type(data) is bytes \
                        and n <= self._BATCH_COALESCE_LIMIT:
                    # native run: consecutive small bytes frames
                    j, total = i, 0
                    while j < nf:
                        d = frames[j]
                        if type(d) is not bytes:
                            break
                        ln = len(d)
                        if ln > self._BATCH_COALESCE_LIMIT or \
                                total + ln + 4 > enc_cap:
                            break
                        total += ln + 4
                        j += 1
                    if j - i > 1:
                        if buf:
                            await self._flush(buf)
                            buf = bytearray()
                        enc = encoder.encode(frames[i:j])
                        if enc is not None:
                            try:
                                await self._flush(enc)
                            finally:
                                enc.release()
                            i = j
                            continue
                        # encode failed (shouldn't): python path
                if n <= self._BATCH_COALESCE_LIMIT:
                    buf += _LEN.pack(n)
                    buf += data
                    if len(buf) >= self._BATCH_COALESCE_LIMIT:
                        await self._flush(buf)
                        buf = bytearray()
                else:
                    # large frame: one vectored flush hands any coalesced
                    # small-frame run + the header + the first chunk to
                    # the stream together (no separate 4-byte write);
                    # remaining chunks flush one timeout window each so
                    # slow links get a window per chunk, not per payload
                    view = memoryview(data)
                    chunk = 4 * self._BATCH_COALESCE_LIMIT
                    head = [_LEN.pack(n), view[:chunk]]
                    if buf:
                        head.insert(0, buf)
                        buf = bytearray()
                    await self._flush_v(head)
                    for off in range(chunk, n, chunk):
                        await self._flush(view[off:off + chunk])
                i += 1
            if buf:
                await self._flush(buf)
        finally:
            for entry in batch:
                if entry is _CLOSE:
                    continue
                p = entry[0]
                if type(p) is list:
                    for x in p:
                        if isinstance(x, Bytes):
                            x.release()
                elif isinstance(p, Bytes):
                    p.release()
        batch.clear()
        for done in dones:
            if not done.done():
                done.set_result(None)
        if close_after:
            await self._stream.close()
            return True
        return False

    # One bulk read per wakeup, then parse every complete frame out of the
    # carry buffer — the old two-awaits-per-frame loop spent ~70% of small-
    # frame time in per-frame asyncio machinery (timeout contexts, wakeups).
    _READ_CHUNK = 256 * 1024

    async def _put_recv(self, item) -> None:
        """Queue parsed frames, releasing their permits if the put is
        interrupted (a cancelled put never inserts — without this, a reader
        cancelled while blocked on a full bounded queue leaks pool bytes)."""
        q = self._recv_q
        if q.maxsize <= 0:
            # unbounded (the common case): skip the awaited put's
            # coroutine round-trip (~1 us per wakeup on the hot drain).
            # Bounded queues keep the awaited path: blocked putters then
            # drain in FIFO among themselves and cannot be starved
            # indefinitely by a put_nowait loop (asyncio.Queue gives no
            # hard slot reservation — a racing new sender can still win
            # the freed slot in the wakeup window, same as always).
            q.put_nowait(item)
            return
        try:
            await self._recv_q.put(item)
        except BaseException:
            if type(item) is Bytes or type(item) is FrameChunk:
                item.release()
            else:
                for b in item:
                    b.release()
            raise

    async def _reader_loop(self) -> None:
        buf = bytearray()
        scanner = native.FrameScanner.create()
        pool = self._limiter.pool
        try:
            while True:
                # The per-frame 5 s read timeout (mod.rs:336) now applies to
                # "progress while a partial frame is pending": a blocked
                # empty buffer waits forever, a half-received frame doesn't.
                if buf:
                    async with asyncio.timeout(READ_TIMEOUT_S):
                        chunk = await self._stream.read_some(self._READ_CHUNK)
                else:
                    chunk = await self._stream.read_some(self._READ_CHUNK)

                # Whole-chunk zero-copy fast path: when the carry buffer is
                # empty and the read chunk ends exactly on a frame boundary
                # (the steady state against a batching writer — one egress
                # flush arrives as one chunk), the chunk object ITSELF
                # becomes the FrameChunk buffer: no carry append, no detach
                # copy. Frames' bytes are then copied exactly once end to
                # end (at decode), like the reference's Bytes-slicing reader.
                if not buf and len(chunk) >= 8 and type(chunk) is bytes:
                    (first_len,) = _LEN.unpack_from(chunk, 0)
                    if first_len <= MAX_MESSAGE_SIZE \
                            and len(chunk) >= 4 + first_len:
                        if scanner is not None and len(chunk) >= 4096:
                            offs, lens, consumed, oversized = scanner.scan(
                                chunk, MAX_MESSAGE_SIZE)
                        else:
                            offs, lens, consumed, oversized = _py_scan_frames(
                                chunk, MAX_MESSAGE_SIZE)
                        if consumed == len(chunk) and not oversized and not (
                                scanner is not None
                                and len(offs) == scanner.max_frames):
                            chunk_permit = None
                            if pool is not None \
                                    and consumed <= pool.capacity:
                                chunk_permit = pool.try_allocate(consumed)
                            if pool is None or chunk_permit is not None:
                                self._m_recv.inc(consumed)
                                await self._put_recv(FrameChunk(
                                    chunk, offs, lens, chunk_permit))
                                continue
                            # pool pressure: the carry path's partial-
                            # handoff machinery below handles it
                buf += chunk

                # Depth-1 fast path (the latency regime): the chunk completed
                # exactly one frame — hand the bare Bytes to the receive
                # queue, skipping the scanner, the batch list, and the
                # pending-deque indirection on the consumer side.
                blen = len(buf)
                if blen >= 4:
                    (length,) = _LEN.unpack_from(buf, 0)
                    if length <= MAX_MESSAGE_SIZE and blen == 4 + length:
                        payload = bytes(memoryview(buf)[4:])
                        permit = None
                        if pool is not None:
                            permit = pool.try_allocate(length)
                            if permit is None:
                                self.flightrec.record("limiter-wait", length)
                                permit = await pool.allocate(length)
                        del buf[:]
                        self._m_recv.inc(blen)
                        await self._put_recv(Bytes(payload, permit))
                        continue

                # Scan every complete frame out of the carry buffer (one C
                # call via native.scan_frames when available) and hand the
                # whole batch to the receive queue in ONE put — per-frame
                # asyncio machinery is what bounded small-frame throughput.
                while len(buf) >= 4:
                    # Peek the first header before scanning: a buffer that
                    # cannot hold one complete frame (the large-frame partial
                    # case) must not pay a scan — the tail streamer below
                    # takes it directly.
                    (first_len,) = _LEN.unpack_from(buf, 0)
                    if first_len > MAX_MESSAGE_SIZE:
                        raise Error(ErrorKind.EXCEEDED_SIZE,
                                    f"peer announced {first_len} B frame")
                    if len(buf) < 4 + first_len:
                        break
                    if scanner is not None and len(buf) >= 4096:
                        offs, lens, consumed, oversized = scanner.scan(
                            buf, MAX_MESSAGE_SIZE)
                    else:
                        # tiny buffers (one or two frames — the latency
                        # regime) scan faster in Python than via ctypes
                        offs, lens, consumed, oversized = _py_scan_frames(
                            buf, MAX_MESSAGE_SIZE)
                    # The peek guarantees at least one complete frame, so the
                    # scan always yields offsets.
                    chunk_permit = None
                    if pool is not None and consumed <= pool.capacity:
                        chunk_permit = pool.try_allocate(consumed)
                    if pool is None or chunk_permit is not None:
                        # Fast path: ONE detached buffer + ONE permit for
                        # the whole parse batch (per-frame Bytes/permits are
                        # what bounded small-frame receive throughput).
                        chunk = FrameChunk(bytes(memoryview(buf)[:consumed]),
                                           offs, lens, chunk_permit)
                        self._m_recv.inc(consumed)
                        del buf[:consumed]
                        await self._put_recv(chunk)
                    else:
                        # Pool pressure: fall back to per-frame permits with
                        # partial handoff — consumers releasing the frames
                        # we already queued are what refill the pool, and a
                        # blocked permit still stops further socket reads.
                        batch: List[Bytes] = []
                        try:
                            mv = memoryview(buf)
                            try:
                                for o, ln in zip(offs, lens):
                                    payload = bytes(mv[o:o + ln])
                                    permit = pool.try_allocate(ln)
                                    if permit is None:
                                        self.flightrec.record(
                                            "limiter-wait", ln)
                                        if batch:
                                            # hand ownership over BEFORE
                                            # the await: a cancelled
                                            # _put_recv releases the frames
                                            # itself, and the outer handler
                                            # must not see them again
                                            handoff, batch = batch, []
                                            await self._put_recv(handoff)
                                        permit = await pool.allocate(ln)
                                    batch.append(Bytes(payload, permit))
                            finally:
                                mv.release()
                        except BaseException:
                            for b in batch:
                                b.release()
                            raise
                        self._m_recv.inc(consumed)
                        if batch:
                            await self._put_recv(
                                batch[0] if len(batch) == 1 else batch)
                        del buf[:consumed]
                    if oversized:
                        # a LATER announced length beyond MAX_MESSAGE_SIZE ⇒
                        # peer violation (preceding good frames were
                        # delivered first)
                        (length,) = _LEN.unpack_from(buf, 0)
                        raise Error(ErrorKind.EXCEEDED_SIZE,
                                    f"peer announced {length} B frame")
                    if scanner is not None and len(offs) == scanner.max_frames:
                        continue  # scanner capacity hit: rescan remainder
                    break

                # Remainder is at most one incomplete frame (at offset 0):
                # acquire the pool permit BEFORE buffering the payload
                # (mod.rs:328 — backpressure lands on the socket), then
                # stream straight into one preallocated buffer, one
                # progress-timeout window per chunk.
                blen = len(buf)
                if blen >= 4:
                    (length,) = _LEN.unpack_from(buf, 0)
                    permit = None
                    if pool is not None:
                        permit = pool.try_allocate(length)
                        if permit is None:
                            self.flightrec.record("limiter-wait", length)
                            permit = await pool.allocate(length)
                    try:
                        out = bytearray(length)
                        pos = blen - 4
                        out[:pos] = memoryview(buf)[4:blen]
                        del buf[:]
                        mv = memoryview(out)
                        try:
                            while pos < length:
                                async with asyncio.timeout(READ_TIMEOUT_S):
                                    chunk = await self._stream.read_some(
                                        min(length - pos, 4 * self._READ_CHUNK))
                                mv[pos:pos + len(chunk)] = chunk
                                pos += len(chunk)
                        finally:
                            mv.release()
                    except BaseException:
                        if permit is not None:
                            permit.release()
                        raise
                    self._m_recv.inc(length + 4)
                    await self._put_recv(Bytes(out, permit))
        except asyncio.CancelledError:
            raise
        except asyncio.IncompleteReadError as exc:
            self._poison(Error(ErrorKind.CONNECTION, "peer closed", exc))
        except Error as err:
            self._poison(err)
        except Exception as exc:
            self._poison(Error(ErrorKind.CONNECTION, f"read failed: {exc!r}", exc))

    def _poison(self, err: Error) -> None:
        if self._error is None:
            self._error = err
        self._closed = True
        # flight recorder: a plain peer FIN is a normal lifecycle event; an
        # I/O failure, oversized frame, or mid-write cancel arms the
        # recorder so the trail hits the diagnostics log at teardown (and
        # right here for un-owned connections nobody will tear down)
        abnormal = err.message != "peer closed"
        self.flightrec.record("error", err.message, abnormal=abnormal)
        if abnormal:
            self.flightrec.maybe_dump(err.message)
        self._stream.abort()
        # Resolve blocked senders, but KEEP the receive side: frames that
        # arrived before the failure are still deliverable (TCP delivers
        # data queued ahead of a FIN; a reader that parses a chunk and hits
        # EOF in the same wakeup must not steal the parsed frames back).
        # The error marker queues BEHIND them; the owner's eventual
        # ``close()`` returns any never-consumed permits to the pool.
        self._drain_send_queue(err)
        # Ask a parked writer task to exit: a task blocked on the send
        # queue holds a reference to this connection forever, which would
        # keep the abandoned-handle finalizer (permit backstop) from ever
        # firing.
        if self._writer_task is not None and not self._writer_task.done():
            try:
                self._send_q.put_nowait(_CLOSE)
            except asyncio.QueueFull:
                self._writer_task.cancel()
        # Wake any blocked receiver. The queued marker is a traceback-free
        # clone: the original's traceback references the reader frame and
        # thus this connection, and the abandoned-handle finalizer holds
        # the queue — a full Error would cycle the connection through the
        # finalizer's own argument and keep GC from ever reclaiming an
        # abandoned handle (the exact leak the finalizer exists to stop).
        try:
            self._recv_q.put_nowait(Error(err.kind, err.message))
        except asyncio.QueueFull:
            pass

    def _drain_send_queue(self, err: Optional[Error]) -> None:
        while True:
            try:
                item = self._send_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if item is _CLOSE:
                continue
            self._account_dropped(item, err)
            payload, done = item[0], item[1]
            if type(payload) is list:
                for p in payload:
                    if isinstance(p, Bytes):
                        p.release()
            elif isinstance(payload, Bytes):
                payload.release()
            if done is not None and not done.done():
                if err is not None:
                    done.set_exception(err)
                else:
                    done.cancel()

    def _drain_queues(self, err: Optional[Error]) -> None:
        """Release every queued frame's pool permit (both directions). A
        closed connection must hand its bytes back to the global pool or
        fan-out clones leak permits until the broker stalls."""
        self._drain_send_queue(err)
        while self._recv_pending:
            item = self._recv_pending.popleft()
            if isinstance(item, (Bytes, FrameChunk)):
                item.release()
        while True:
            try:
                item = self._recv_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if isinstance(item, list):
                for p in item:
                    p.release()
            elif isinstance(item, (Bytes, FrameChunk)):
                item.release()

    def _check(self) -> None:
        if self._error is not None:
            raise self._error
        if self._closed:
            raise Error(ErrorKind.CONNECTION, "connection closed")

    # -- public API (parity mod.rs:223-306) ---------------------------------

    async def send_message(self, message: Message, flush: bool = False) -> None:
        await self.send_raw(serialize(message), flush=flush)

    async def send_raw(self, raw, flush: bool = False, cls: int = 0) -> None:
        """Queue a pre-serialized frame (``bytes`` or :class:`Bytes`).

        With ``flush=True``, wait until the frame hits the stream — used by
        handshakes; the hot path queues and returns (reference
        send_message_raw semantics).

        ``cls`` is the frame's flow class (flowclass taxonomy; 0/control
        default fits the protocol traffic this entry point mostly carries)
        — it rides the queue entry so the writer can account per-class
        queue delay and egress volume at dequeue.

        Inline fast path: a flushed small frame on an idle link is written
        directly from the caller's task (no writer-task wakeup, no done
        future) — one scheduling round instead of three per handshake
        message. Only taken when the send queue is empty AND the writer
        isn't mid-write (``_write_mutex``), so frames can never reorder or
        interleave; the mutex acquire is non-yielding in that state, which
        makes check-then-acquire atomic on the single loop.
        """
        self._check()
        if flush and self._send_q.empty() and not self._write_mutex.locked():
            data = raw.data if isinstance(raw, Bytes) else raw
            if type(data) is bytes and len(data) <= self._BATCH_COALESCE_LIMIT:
                await self._write_mutex.acquire()
                try:
                    one = bytearray(_LEN.pack(len(data)))
                    one += data
                    await self._flush(one)
                except asyncio.CancelledError:
                    # cancelled mid-write: part of the frame may already be
                    # on the stream (transports commit incrementally), so
                    # the link's framing can no longer be trusted — poison,
                    # exactly like the writer loop cancelled mid-flush
                    self._poison(Error(ErrorKind.CONNECTION,
                                       "send cancelled mid-write"))
                    raise
                except Exception as exc:
                    err = Error(ErrorKind.CONNECTION,
                                f"write failed: {exc!r}", exc)
                    self._poison(err)
                    raise err
                finally:
                    if isinstance(raw, Bytes):
                        raw.release()
                    self._write_mutex.release()
                # inline path: zero queue delay by construction, so only
                # the volume counters move
                metrics_mod.CLASS_FRAMES_OUT[cls & 3].inc()
                metrics_mod.CLASS_BYTES_OUT[cls & 3].inc(len(data) + 4)
                ledger_mod.on_transit(cls & 3, 1, self.ledger_peer)
                return
        done = asyncio.get_running_loop().create_future() if flush else None
        nb = (len(raw.data) if isinstance(raw, Bytes) else len(raw)) + 4
        stamp = (time.monotonic(), cls & 3, 1, nb, 1)
        q = self._send_q
        if q.maxsize <= 0:
            # unbounded (the default): skip the awaited put's coroutine
            # round-trip on the hot path. Bounded queues keep the awaited
            # path: blocked senders queue FIFO among themselves rather
            # than losing every freed slot to a put_nowait fast path
            # (asyncio.Queue has no hard slot reservation, so a racing
            # sender can still occasionally win the wakeup window).
            q.put_nowait((raw, done, stamp))
        else:
            await q.put((raw, done, stamp))
        ledger_mod.note_queued(cls & 3, 1)
        self._ensure_writer()
        if self._error is not None:  # poisoned while enqueueing
            raise self._error
        if done is not None:
            await done

    def send_raw_nowait(self, raw, cls: int = 2) -> None:
        """Queue a frame without awaiting; raises ``asyncio.QueueFull`` when
        the per-connection queue bound is hit (callers treat that as a
        failed send). Used by the device-plane egress so one backpressured
        peer can't stall the pump (hence the ``live`` class default)."""
        self._check()
        cls &= 3
        nb = (len(raw.data) if isinstance(raw, Bytes) else len(raw)) + 4
        try:
            self._send_q.put_nowait(
                (raw, None, (time.monotonic(), cls, 1, nb, 1)))
        except asyncio.QueueFull:
            self.flightrec.record("backpressure", "send queue full")
            raise
        ledger_mod.note_queued(cls, 1)
        self._ensure_writer()
        if self._error is not None:
            raise self._error

    async def send_raw_many(self, raws: list, flush: bool = False,
                            cls: int = 2, nframes=None, nbytes=None) -> None:
        """Queue a whole batch of pre-serialized frames as ONE queue entry
        (one writer wakeup for the lot) — the routing loops build per-peer
        batches and hand them over here.

        ``cls``/``nframes``/``nbytes`` stamp the entry for per-class
        accounting: ``None`` means count the batch here (len + byte walk);
        a caller that already accounted its frames per-class (mixed-class
        plan bincounts) passes ``nframes=0, nbytes=0`` so the writer only
        observes the queue delay.

        Ownership semantics are stricter than :meth:`send_raw`: every
        :class:`Bytes` in ``raws`` is ALWAYS released by this connection —
        by the writer after flushing, by the poison drain, or right here
        when the frames never made it into the queue — so callers must not
        release on failure (no double-release of fan-out clones)."""
        try:
            self._check()
            done = asyncio.get_running_loop().create_future() if flush else None
        except BaseException:
            for p in raws:
                if isinstance(p, Bytes):
                    p.release()
            raise
        if nframes is None:
            nframes = len(raws)
        if nbytes is None:
            nbytes = sum(len(p.data) if isinstance(p, Bytes) else len(p)
                         for p in raws) + 4 * len(raws)
        stamp = (time.monotonic(), cls & 3, nframes, nbytes, len(raws))
        try:
            q = self._send_q
            if q.maxsize <= 0:
                q.put_nowait((raws, done, stamp))  # unbounded: no coroutine hop
                ledger_mod.note_queued(cls & 3, len(raws))
                self._ensure_writer()
            else:
                await q.put((raws, done, stamp))  # bounded: behind waiters
                ledger_mod.note_queued(cls & 3, len(raws))
                self._ensure_writer()
        except BaseException:
            # cancelled while blocked on a bounded queue: never inserted
            for p in raws:
                if isinstance(p, Bytes):
                    p.release()
            raise
        if self._error is not None:
            # poisoned around the enqueue: the poison drain may have run
            # before our insert landed, so drain again (idempotent) to
            # guarantee the batch's permits return to the pool
            self._drain_queues(self._error)
            raise self._error
        if done is not None:
            await done

    def send_encoded_nowait(self, data, owner=None, cls: int = 2,
                            nframes: int = 0, nbytes=None,
                            count: Optional[int] = None) -> None:
        """Queue an ALREADY length-delimited byte stream (one or many
        frames, each u32-BE-prefixed) to be written verbatim — the
        device-plane egress path: the native engine frames a whole step's
        deliveries per user in C, so the writer's only job is the flush.
        ``data`` may be a memoryview over the step's shared egress buffer;
        pass the buffer's holder (e.g. the ``EgressStreams``) as ``owner``
        so a pooled buffer cannot be recycled under the pending write.

        The stream is opaque here (already framed), so callers that know
        the frame count pass ``nframes``; ``nbytes`` defaults to the
        stream's length (header bytes included — it IS the wire image).
        ``count`` is the REAL frame count for the conservation ledger
        when ``nframes`` deliberately stays 0 (class volume pre-counted
        at the routing decision); it defaults to ``nframes``."""
        self._check()
        if nbytes is None:
            nbytes = len(data)
        stamp = (time.monotonic(), cls & 3, nframes, nbytes,
                 nframes if count is None else count)
        try:
            self._send_q.put_nowait((PreEncoded(data, owner), None, stamp))
        except asyncio.QueueFull:
            self.flightrec.record("backpressure", "send queue full")
            raise
        ledger_mod.note_queued(cls & 3, stamp[4])
        self._ensure_writer()
        if self._error is not None:
            raise self._error

    async def send_encoded(self, data, owner=None, flush: bool = False,
                           cls: int = 2, nframes: int = 0,
                           nbytes=None, count: Optional[int] = None) -> None:
        """Awaited twin of :meth:`send_encoded_nowait`: queues behind a
        bounded send queue instead of raising ``QueueFull`` — the routing
        loops' pre-encoded egress handoff (one writer entry, one verbatim
        flush for a whole per-peer fan-out batch)."""
        self._check()
        done = asyncio.get_running_loop().create_future() if flush else None
        if nbytes is None:
            nbytes = len(data)
        real = nframes if count is None else count
        q = self._send_q
        entry = (PreEncoded(data, owner), done,
                 (time.monotonic(), cls & 3, nframes, nbytes, real))
        if q.maxsize <= 0:
            q.put_nowait(entry)  # unbounded: no coroutine hop
        else:
            await q.put(entry)
        ledger_mod.note_queued(cls & 3, real)
        self._ensure_writer()
        if self._error is not None:
            raise self._error
        if done is not None:
            await done

    def send_raw_many_nowait(self, raws: list, cls: int = 2,
                             nframes=None, nbytes=None) -> None:
        """Batch variant of :meth:`send_raw_nowait` (one entry, no await),
        with :meth:`send_raw_many`'s ownership rule: the frames are always
        released by the connection, never by the caller."""
        try:
            self._check()
            if nframes is None:
                nframes = len(raws)
            if nbytes is None:
                nbytes = sum(len(p.data) if isinstance(p, Bytes) else len(p)
                             for p in raws) + 4 * len(raws)
            self._send_q.put_nowait(
                (raws, None,
                 (time.monotonic(), cls & 3, nframes, nbytes, len(raws))))
            ledger_mod.note_queued(cls & 3, len(raws))
            self._ensure_writer()
        except BaseException:
            for p in raws:
                if isinstance(p, Bytes):
                    p.release()
            raise
        if self._error is not None:
            self._drain_queues(self._error)
            raise self._error

    async def recv_message(self) -> Message:
        """Receive + decode one message, copying payload views out of the
        receive buffer so the pool permit can be released immediately. Hot
        paths that fan raw frames out should use :meth:`recv_raw` and
        release after the last send instead."""
        raw = await self.recv_raw()
        try:
            return deserialize_owned(raw.data)
        finally:
            raw.release()

    async def recv_raw(self) -> Bytes:
        """Receive one frame as refcounted :class:`Bytes` (permit attached)."""
        pending = self._recv_pending
        while not pending:
            if self._error is not None and self._recv_q.empty():
                raise self._error
            item = await self._recv_q.get()
            if type(item) is Bytes:  # depth-1 fast path: bare frame
                return item
            if type(item) is FrameChunk:
                pending.append(item)
                break
            if isinstance(item, Error):
                # keep the poison visible to subsequent callers
                try:
                    self._recv_q.put_nowait(item)
                except asyncio.QueueFull:
                    pass
                raise item
            pending.extend(item)
        head = pending[0]
        if type(head) is FrameChunk:
            b = head.take()
            if head.remaining == 0:
                pending.popleft()
            return b
        return pending.popleft()

    async def _fill_pending(self, limit: int) -> None:
        """Block until at least one frame is pending, then opportunistically
        drain whatever else is already queued (up to ~``limit`` frames)."""
        pending = self._recv_pending
        while not pending:
            if self._error is not None and self._recv_q.empty():
                raise self._error
            item = await self._recv_q.get()
            if type(item) is Bytes or type(item) is FrameChunk:
                pending.append(item)
                break
            if isinstance(item, Error):
                try:
                    self._recv_q.put_nowait(item)
                except asyncio.QueueFull:
                    pass
                raise item
            pending.extend(item)
        count = sum(i.remaining if type(i) is FrameChunk else 1
                    for i in pending)
        while count < limit:
            try:
                item = self._recv_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if type(item) is Bytes:
                pending.append(item)
                count += 1
                continue
            if type(item) is FrameChunk:
                pending.append(item)
                count += item.remaining
                continue
            if isinstance(item, Error):
                # deliver the batch first; the error surfaces on the next call
                try:
                    self._recv_q.put_nowait(item)
                except asyncio.QueueFull:
                    pass
                break
            pending.extend(item)
            count += len(item)

    async def recv_raw_many(self, limit: int = 4096) -> List[Bytes]:
        """Receive every frame currently available (at least one; blocks
        only when none are pending). The routing loops drain with this so
        one task wakeup routes a whole parse batch."""
        await self._fill_pending(limit)
        pending = self._recv_pending
        out: List[Bytes] = []
        while pending and len(out) < limit:
            head = pending[0]
            if type(head) is FrameChunk:
                while head.remaining and len(out) < limit:
                    out.append(head.take())
                if head.remaining == 0:
                    pending.popleft()
            else:
                out.append(pending.popleft())
        return out

    async def recv_frames(self, limit: int = 4096) -> list:
        """Receive pending traffic as a list of :class:`Bytes` and
        :class:`FrameChunk` items — the zero-materialization drain for
        consumers that process whole batches (``Client.receive_messages``).
        ``limit`` is approximate: the last chunk is handed over whole.
        The caller owns every item: ``release()`` each when done."""
        await self._fill_pending(limit)
        pending = self._recv_pending
        out: list = []
        count = 0
        while pending and count < limit:
            head = pending.popleft()
            count += head.remaining if type(head) is FrameChunk else 1
            out.append(head)
        return out

    async def soft_close(self) -> None:
        """Flush queued frames, then close the write side (parity
        ``soft_close``, protocols/mod.rs — QUIC does a real finish/stopped
        dance; for byte streams this is flush+FIN)."""
        if self._error is not None:
            raise self._error
        self._closed = True
        self.flightrec.record("close", "soft")
        if self._writer_task is None:
            # nothing was ever queued: flush is trivially done — close the
            # write side directly (under the mutex so an in-flight inline
            # write completes first)
            try:
                async with asyncio.timeout(WRITE_TIMEOUT_S):
                    async with self._write_mutex:
                        await self._stream.close()
            except Exception:
                pass
            self._reader_task.cancel()
            return
        await self._send_q.put(_CLOSE)
        try:
            async with asyncio.timeout(WRITE_TIMEOUT_S):
                await asyncio.shield(self._writer_task)
        except (asyncio.TimeoutError, asyncio.CancelledError, Error):
            pass
        except Exception:
            pass
        self._reader_task.cancel()

    def close(self) -> None:
        """Tear down immediately (abort both tasks, return queued permits)."""
        self._closed = True
        self.flightrec.record("close", "abort")
        if self._writer_task is not None:
            self._writer_task.cancel()
        self._reader_task.cancel()
        self._stream.abort()
        self._drain_queues(self._error)

    @property
    def is_closed(self) -> bool:
        return self._closed or self._error is not None


class UnfinalizedConnection(abc.ABC):
    """An accepted-but-not-ready connection; ``finalize`` completes any
    handshake and spawns the actor tasks (parity mod.rs:64-81 — accept is
    kept cheap so one slow handshake can't stall the accept loop)."""

    @abc.abstractmethod
    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection: ...


class Listener(abc.ABC):
    """Bound server socket: ``accept`` yields unfinalized connections."""

    @abc.abstractmethod
    async def accept(self) -> UnfinalizedConnection: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Protocol(abc.ABC):
    """A transport implementation (parity ``Protocol`` trait, mod.rs:40-63)."""

    name: str = "?"

    @classmethod
    @abc.abstractmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection: ...

    @classmethod
    @abc.abstractmethod
    async def bind(cls, endpoint: str, certificate=None,
                   reuse_port: bool = False) -> Listener:
        """``reuse_port=True`` requests SO_REUSEPORT so N worker shards
        can bind the same endpoint and let the kernel spread accepts
        (transports without a kernel socket — Memory — reject it)."""
