"""The transport-generic connection machinery.

Capability parity with cdn-proto/src/connection/protocols/mod.rs:

- ``Protocol`` — connect/bind with associated listener + unfinalized
  connection types (mod.rs:40-81).
- ``Connection`` — the uniform handle: two actor tasks (writer-drain and
  reader-pump) bridged to callers by queues (mod.rs:139-217), with
  ``send_message[_raw]`` / ``recv_message[_raw]`` / ``soft_close``
  (mod.rs:223-306).
- Length-delimited framing: u32 big-endian length prefix then payload, max
  ``MAX_MESSAGE_SIZE``, 5 s per-frame read/write timeouts
  (mod.rs:309-394; cdn-proto/src/lib.rs:25).
- Backpressure lands on the socket, not the router (mod.rs:328): frames
  larger than the read chunk acquire their limiter byte-permit before the
  payload is buffered; small frames parsed out of an already-read chunk
  acquire theirs before entering the receive queue, so the unpermitted
  overshoot is bounded by ``Connection._READ_CHUNK`` per connection and
  a blocked permit still stops further socket reads.
"""

from __future__ import annotations

import abc
import asyncio
import struct
from typing import Optional

from pushcdn_tpu.proto import MAX_MESSAGE_SIZE
from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Bytes, Limiter, NO_LIMIT
from pushcdn_tpu.proto.message import Message, deserialize, materialize, serialize
from pushcdn_tpu.proto import metrics as metrics_mod

# Parity: 5 s read/write timeouts (protocols/mod.rs:336, :368, :379) and a
# 5 s connect timeout (tcp.rs).
WRITE_TIMEOUT_S = 5.0
READ_TIMEOUT_S = 5.0
CONNECT_TIMEOUT_S = 5.0

_LEN = struct.Struct(">I")

_CLOSE = object()  # sentinel queued to ask the writer task to soft-close


class RawStream(abc.ABC):
    """Minimal async byte-stream pair every transport lowers to."""

    @abc.abstractmethod
    async def read_exactly(self, n: int) -> bytes: ...

    async def read_some(self, max_n: int) -> bytes:
        """Return at least 1 and at most ``max_n`` bytes; raise
        ``IncompleteReadError`` at EOF. Transports override this with a
        real bulk read — the reader loop uses it to parse many small
        frames per wakeup instead of two awaits per frame."""
        return await self.read_exactly(1)

    @abc.abstractmethod
    async def write(self, data) -> None:
        """Buffer ``data`` and flush (may await backpressure)."""

    @abc.abstractmethod
    async def close(self) -> None:
        """Flush and close the write side gracefully."""

    @abc.abstractmethod
    def abort(self) -> None:
        """Tear down immediately."""


class AsyncioStream(RawStream):
    """RawStream over an asyncio (StreamReader, StreamWriter) pair."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    async def read_exactly(self, n: int) -> bytes:
        return await self.reader.readexactly(n)

    async def read_some(self, max_n: int) -> bytes:
        data = await self.reader.read(max_n)
        if not data:
            raise asyncio.IncompleteReadError(b"", 1)
        return data

    async def write(self, data) -> None:
        self.writer.write(bytes(data) if isinstance(data, memoryview) else data)
        await self.writer.drain()

    async def close(self) -> None:
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass

    def abort(self) -> None:
        try:
            self.writer.transport.abort()
        except Exception:
            try:
                self.writer.close()
            except Exception:
                pass


class Connection:
    """Uniform connection handle with actor-style reader/writer tasks.

    Shape parity with protocols/mod.rs:139-217: a writer task drains a send
    queue into the stream; a reader task pumps length-delimited frames into
    a receive queue (acquiring limiter permits first). Any I/O error poisons
    the connection: both queues wake with the error and subsequent calls
    raise ``Error(CONNECTION)`` — the caller's policy is removal/reconnect
    (fault detection *is* "send failed", tasks/broker/sender.rs:35-43).
    """

    def __init__(self, stream: RawStream, limiter: Limiter = NO_LIMIT,
                 label: str = "?"):
        self._stream = stream
        self._limiter = limiter
        self.label = label
        qsize = limiter.queue_size()
        self._send_q: asyncio.Queue = asyncio.Queue(maxsize=qsize)
        self._recv_q: asyncio.Queue = asyncio.Queue(maxsize=qsize)
        self._error: Optional[Error] = None
        self._closed = False
        self._writer_task = asyncio.create_task(self._writer_loop())
        self._reader_task = asyncio.create_task(self._reader_loop())

    # -- actor loops --------------------------------------------------------

    # Batch small frames into one buffer per flush: per-frame event-loop +
    # syscall overhead dominates ≤1 KB frames otherwise (BASELINE.md soft
    # spot). Each flush unit stays under this size so the per-flush 5 s
    # timeout keeps the same granularity the old per-frame timeout had;
    # frames above the limit are written directly, no extra copy.
    _BATCH_COALESCE_LIMIT = 64 * 1024

    async def _flush(self, buf: bytearray) -> None:
        """One bounded write under its own timeout; BYTES_SENT counts only
        bytes that actually flushed."""
        async with asyncio.timeout(WRITE_TIMEOUT_S):
            await self._stream.write(buf)
        metrics_mod.BYTES_SENT.inc(len(buf))

    async def _writer_loop(self) -> None:
        batch: list = []
        try:
            while True:
                item = await self._send_q.get()
                if item is _CLOSE:
                    await self._stream.close()
                    return
                # Drain everything queued right now into one write batch.
                batch = [item]
                while len(batch) < 512:
                    try:
                        nxt = self._send_q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    batch.append(nxt)
                    if nxt is _CLOSE:
                        break

                buf = bytearray()
                dones = []
                close_after = False
                try:
                    for entry in batch:
                        if entry is _CLOSE:
                            close_after = True
                            break
                        payload, done = entry
                        data = payload.data if isinstance(payload, Bytes) else payload
                        n = len(data)
                        if n <= self._BATCH_COALESCE_LIMIT:
                            buf += _LEN.pack(n)
                            buf += data
                            if len(buf) >= self._BATCH_COALESCE_LIMIT:
                                await self._flush(buf)
                                buf = bytearray()
                        else:
                            if buf:
                                await self._flush(buf)
                                buf = bytearray()
                            await self._flush(bytearray(_LEN.pack(n)))
                            # large frames flush in bounded chunks so slow
                            # links get a timeout window per chunk, not one
                            # window for the whole payload
                            view = memoryview(data)
                            chunk = 4 * self._BATCH_COALESCE_LIMIT
                            for off in range(0, n, chunk):
                                await self._flush(bytearray(view[off:off + chunk]))
                        if done is not None:
                            dones.append(done)
                    if buf:
                        await self._flush(buf)
                finally:
                    for entry in batch:
                        if entry is not _CLOSE and isinstance(entry[0], Bytes):
                            entry[0].release()
                batch = []
                for done in dones:
                    if not done.done():
                        done.set_result(None)
                if close_after:
                    await self._stream.close()
                    return
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            err = Error(ErrorKind.CONNECTION, f"write failed: {exc!r}", exc)
            # flush=True senders whose entries we already dequeued must see
            # the failure (they are beyond _poison's queue drain)
            for entry in batch:
                if entry is not _CLOSE and entry[1] is not None \
                        and not entry[1].done():
                    entry[1].set_exception(err)
            self._poison(err)

    # One bulk read per wakeup, then parse every complete frame out of the
    # carry buffer — the old two-awaits-per-frame loop spent ~70% of small-
    # frame time in per-frame asyncio machinery (timeout contexts, wakeups).
    _READ_CHUNK = 256 * 1024

    async def _reader_loop(self) -> None:
        buf = bytearray()
        try:
            while True:
                # The per-frame 5 s read timeout (mod.rs:336) now applies to
                # "progress while a partial frame is pending": a blocked
                # empty buffer waits forever, a half-received frame doesn't.
                if buf:
                    async with asyncio.timeout(READ_TIMEOUT_S):
                        chunk = await self._stream.read_some(self._READ_CHUNK)
                else:
                    chunk = await self._stream.read_some(self._READ_CHUNK)
                buf += chunk
                off = 0
                blen = len(buf)
                # one exported view per chunk: slicing it yields bytes in a
                # single copy (a bytearray slice + bytes() would be two);
                # must be released before the bytearray is resized
                mv = memoryview(buf)
                while blen - off >= 4:
                    (length,) = _LEN.unpack_from(buf, off)
                    if length > MAX_MESSAGE_SIZE:
                        mv.release()
                        raise Error(ErrorKind.EXCEEDED_SIZE,
                                    f"peer announced {length} B frame")
                    if blen - off - 4 < length:
                        # Incomplete frame: acquire the pool permit BEFORE
                        # buffering the remainder (mod.rs:328 — backpressure
                        # lands on the socket), then stream straight into
                        # one preallocated buffer (no reassembly copy), one
                        # progress-timeout window per chunk rather than one
                        # for the whole payload.
                        permit = await self._limiter.allocate_message_bytes(
                            length)
                        try:
                            out = bytearray(length)
                            pos = blen - off - 4
                            out[:pos] = mv[off + 4:blen]
                            mv.release()
                            del buf[:]
                            off = 0
                            blen = 0
                            mv = memoryview(out)
                            while pos < length:
                                async with asyncio.timeout(READ_TIMEOUT_S):
                                    chunk = await self._stream.read_some(
                                        min(length - pos, 4 * self._READ_CHUNK))
                                mv[pos:pos + len(chunk)] = chunk
                                pos += len(chunk)
                        except BaseException:
                            if permit is not None:
                                permit.release()
                            raise
                        metrics_mod.BYTES_RECV.inc(length + 4)
                        await self._recv_q.put(Bytes(out, permit))
                        continue
                    # Complete frame in the buffer. The permit is acquired
                    # after the bytes were read — the overshoot is bounded
                    # by _READ_CHUNK, and a blocked permit still stops the
                    # socket (no further read_some until the put succeeds).
                    payload = bytes(mv[off + 4:off + 4 + length])
                    off += 4 + length
                    permit = await self._limiter.allocate_message_bytes(length)
                    metrics_mod.BYTES_RECV.inc(length + 4)
                    await self._recv_q.put(Bytes(payload, permit))
                else:
                    # loop fell through (≤3 leftover bytes): release the
                    # view so the carry buffer can be resized
                    mv.release()
                if off:
                    del buf[:off]
        except asyncio.CancelledError:
            raise
        except asyncio.IncompleteReadError as exc:
            self._poison(Error(ErrorKind.CONNECTION, "peer closed", exc))
        except Error as err:
            self._poison(err)
        except Exception as exc:
            self._poison(Error(ErrorKind.CONNECTION, f"read failed: {exc!r}", exc))

    def _poison(self, err: Error) -> None:
        if self._error is None:
            self._error = err
        self._closed = True
        self._stream.abort()
        self._drain_queues(err)
        # Wake any blocked receiver.
        try:
            self._recv_q.put_nowait(err)
        except asyncio.QueueFull:
            pass

    def _drain_queues(self, err: Optional[Error]) -> None:
        """Release every queued frame's pool permit (both directions). A
        closed/poisoned connection must hand its bytes back to the global
        pool or fan-out clones leak permits until the broker stalls."""
        while True:
            try:
                item = self._send_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if item is _CLOSE:
                continue
            payload, done = item
            if isinstance(payload, Bytes):
                payload.release()
            if done is not None and not done.done():
                if err is not None:
                    done.set_exception(err)
                else:
                    done.cancel()
        while True:
            try:
                item = self._recv_q.get_nowait()
            except asyncio.QueueEmpty:
                break
            if isinstance(item, Bytes):
                item.release()

    def _check(self) -> None:
        if self._error is not None:
            raise self._error
        if self._closed:
            raise Error(ErrorKind.CONNECTION, "connection closed")

    # -- public API (parity mod.rs:223-306) ---------------------------------

    async def send_message(self, message: Message, flush: bool = False) -> None:
        await self.send_raw(serialize(message), flush=flush)

    async def send_raw(self, raw, flush: bool = False) -> None:
        """Queue a pre-serialized frame (``bytes`` or :class:`Bytes`).

        With ``flush=True``, wait until the frame hits the stream — used by
        handshakes; the hot path queues and returns (reference
        send_message_raw semantics).
        """
        self._check()
        done = asyncio.get_running_loop().create_future() if flush else None
        await self._send_q.put((raw, done))
        if self._error is not None:  # poisoned while enqueueing
            raise self._error
        if done is not None:
            await done

    def send_raw_nowait(self, raw) -> None:
        """Queue a frame without awaiting; raises ``asyncio.QueueFull`` when
        the per-connection queue bound is hit (callers treat that as a
        failed send). Used by the device-plane egress so one backpressured
        peer can't stall the pump."""
        self._check()
        self._send_q.put_nowait((raw, None))
        if self._error is not None:
            raise self._error

    async def recv_message(self) -> Message:
        """Receive + decode one message, copying payload views out of the
        receive buffer so the pool permit can be released immediately. Hot
        paths that fan raw frames out should use :meth:`recv_raw` and
        release after the last send instead."""
        raw = await self.recv_raw()
        try:
            return materialize(deserialize(raw.data))
        finally:
            raw.release()

    async def recv_raw(self) -> Bytes:
        """Receive one frame as refcounted :class:`Bytes` (permit attached)."""
        if self._error is not None and self._recv_q.empty():
            raise self._error
        item = await self._recv_q.get()
        if isinstance(item, Error):
            # keep the poison visible to subsequent callers
            try:
                self._recv_q.put_nowait(item)
            except asyncio.QueueFull:
                pass
            raise item
        return item

    async def soft_close(self) -> None:
        """Flush queued frames, then close the write side (parity
        ``soft_close``, protocols/mod.rs — QUIC does a real finish/stopped
        dance; for byte streams this is flush+FIN)."""
        if self._error is not None:
            raise self._error
        self._closed = True
        await self._send_q.put(_CLOSE)
        try:
            async with asyncio.timeout(WRITE_TIMEOUT_S):
                await asyncio.shield(self._writer_task)
        except (asyncio.TimeoutError, asyncio.CancelledError, Error):
            pass
        except Exception:
            pass
        self._reader_task.cancel()

    def close(self) -> None:
        """Tear down immediately (abort both tasks, return queued permits)."""
        self._closed = True
        self._writer_task.cancel()
        self._reader_task.cancel()
        self._stream.abort()
        self._drain_queues(self._error)

    @property
    def is_closed(self) -> bool:
        return self._closed or self._error is not None


class UnfinalizedConnection(abc.ABC):
    """An accepted-but-not-ready connection; ``finalize`` completes any
    handshake and spawns the actor tasks (parity mod.rs:64-81 — accept is
    kept cheap so one slow handshake can't stall the accept loop)."""

    @abc.abstractmethod
    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection: ...


class Listener(abc.ABC):
    """Bound server socket: ``accept`` yields unfinalized connections."""

    @abc.abstractmethod
    async def accept(self) -> UnfinalizedConnection: ...

    @abc.abstractmethod
    async def close(self) -> None: ...


class Protocol(abc.ABC):
    """A transport implementation (parity ``Protocol`` trait, mod.rs:40-63)."""

    name: str = "?"

    @classmethod
    @abc.abstractmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection: ...

    @classmethod
    @abc.abstractmethod
    async def bind(cls, endpoint: str, certificate=None) -> Listener: ...
