"""QUIC transport — gated.

The reference's fourth transport is QUIC via quinn (protocols/quic.rs:37-277:
one bidirectional stream bootstrapped with a single byte, 5 s keep-alive, a
real soft-close via finish + stopped). This environment has no QUIC stack
(no aioquic, and installing packages is disallowed), so the class exists to
keep the transport registry complete and fail with a clear error if
selected. The `Protocol` seam means dropping a real implementation in later
touches nothing else.
"""

from __future__ import annotations

from pushcdn_tpu.proto.error import ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import Connection, Listener, Protocol

_MSG = ("QUIC transport is unavailable in this build (no QUIC stack in the "
        "environment); use Tcp, TcpTls, or Memory")


class Quic(Protocol):
    name = "quic"

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        bail(ErrorKind.CONNECTION, _MSG)

    @classmethod
    async def bind(cls, endpoint: str, certificate=None) -> Listener:
        bail(ErrorKind.CONNECTION, _MSG)
