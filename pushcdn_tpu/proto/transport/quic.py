"""QUIC-class UDP transport.

Capability parity with cdn-proto/src/connection/protocols/quic.rs:37-277
(quinn): a connection-oriented, reliable, ordered byte stream over UDP with

- a connect handshake (SYN/SYNACK with client-chosen connection id — the
  analog of quinn's connection establishment),
- exactly one bidirectional stream per connection, bootstrapped by a single
  byte written by the client and consumed by the server during finalize
  (parity quic.rs:148-149, :224-266 — quinn streams don't exist on the
  acceptor until bytes arrive, so the reference sends one byte; we mirror
  the wire behavior),
- 5 s keep-alive pings and an idle timeout (parity quic.rs keep_alive),
- a real soft-close: FIN is retransmitted until FINACK'd, waiting up to 3 s
  (parity quic.rs finish + stopped with a 3 s window),
- loss recovery: cumulative ACKs + timer-driven retransmission of the
  earliest unacked segment, and a byte-denominated send window so a slow
  receiver backpressures the sender,
- congestion control (the analog of quinn's CC stack, quic.rs:37-146):
  NewReno — slow start / congestion avoidance over an RFC 6298 RTT
  estimator, 3-dup-ACK fast retransmit + fast recovery with partial-ACK
  retransmission, RTO collapse to 2 segments, and token-bucket pacing at
  ~1.25x cwnd/srtt so a window never lands on the path as one burst,
- path-MTU probing (the analog of QUIC DPLPMTUD, RFC 9000 §14.3): each
  direction probes with padded datagrams and adopts the largest size the
  peer acknowledges — on loopback/jumbo paths segments grow from 1200 B
  to up to ~64 KB, cutting per-datagram syscall cost ~50×,
- delayed ACKs: in-order data is acknowledged on a short timer or every
  ACK_EVERY_BYTES, out-of-order data immediately (so fast-retransmit
  still sees duplicate ACKs promptly).

This is not RFC 9000 (the environment ships no QUIC stack and installing
one is disallowed); it is a minimal reliable-datagram transport with the
same operational envelope, behind the same `Protocol` seam, so a real QUIC
stack can replace the packet layer without touching callers.

**Encryption:** the stream is TLS 1.3-secured, the same layering real QUIC
uses (RFC 9001 runs the TLS handshake over QUIC's reliable crypto
streams): the ARQ provides reliable ordered delivery and
``TlsStream`` (ssl.MemoryBIO) runs the TLS state machine over it, keyed
by the same local/production CA plumbing as the TcpTls edge — parity with
the reference's quinn+rustls configuration (quic.rs:37-146; cert config
:52-86). The bootstrap byte and all framed messages ride inside TLS;
only SYN/ACK/PROBE/PING control datagrams and TLS records are visible on
the wire.

Packet layout (all integers big-endian):
    [1B type][8B conn_id][type-specific]
    SYN/SYNACK/PING/RST: nothing further
    DATA:   [8B stream offset][payload <= negotiated MTU]
    ACK:    [8B cumulative ack offset][4B ack_delay us]
    FIN:    [8B final stream offset]
    FINACK: nothing further
    PROBE:  [4B datagram length][zero padding to that length]
    PROBEACK: [4B datagram length]
"""

from __future__ import annotations

import asyncio
import errno
import logging
import os
import ssl
import struct
import time
from collections import deque
from itertools import islice
from typing import Dict, Optional, Tuple

from pushcdn_tpu.proto.crypto.tls import (
    Certificate,
    client_context_for,
    local_certificate,
)
from pushcdn_tpu.proto.error import ErrorKind, bail, parse_endpoint
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.transport.base import (
    CONNECT_TIMEOUT_S,
    Connection,
    Listener,
    Protocol,
    RawStream,
    UnfinalizedConnection,
)
from pushcdn_tpu.proto.transport.tls_stream import TlsStream

logger = logging.getLogger("pushcdn.transport")

(_SYN, _SYNACK, _DATA, _ACK, _FIN, _FINACK, _PING, _RST,
 _PROBE, _PROBEACK) = range(1, 11)


def _socket_for_first_usable(infos, action):
    """Iterate ALL ``getaddrinfo`` results (dual-stack hostnames resolve
    to v6 first on many hosts; a v6-less host must fall through to the v4
    record — the behavior ``create_datagram_endpoint`` used to give this
    transport). ``action(sock, addr)`` attempts connect/bind; the first
    family that completes wins. Raises the LAST OSError when none do."""
    import socket as _socket
    last_exc: Optional[Exception] = None
    for family, stype, _pr, _cn, addr in infos:
        try:
            sock = _socket.socket(family, stype)
        except OSError as exc:
            last_exc = exc
            continue
        try:
            sock.setblocking(False)
            _tune_socket(sock)
            action(sock, addr)
            return sock
        except (OSError, TypeError, ValueError) as exc:
            # OSError: unroutable/unsupported family on this host;
            # Type/ValueError: family/address-shape mismatch from a
            # degenerate resolver row — either way, try the next record
            last_exc = exc
            sock.close()
    if isinstance(last_exc, OSError):
        raise last_exc
    # callers translate OSError into the typed Error(CONNECTION); a
    # degenerate row's TypeError/ValueError must not escape as-is
    raise OSError(f"no usable getaddrinfo result ({last_exc!r})")


def _tune_socket(sock) -> None:
    import socket as _socket
    for opt in (_socket.SO_RCVBUF, _socket.SO_SNDBUF):
        try:
            sock.setsockopt(_socket.SOL_SOCKET, opt, SOCK_BUF)
        except OSError:
            pass
    # Path-MTU discovery needs the don't-fragment bit (RFC 8899 §4.1):
    # without it the kernel IP-fragments oversized probes, they arrive
    # reassembled, and probing "confirms" a size the path can't carry as
    # single packets. With DF set, an oversized send fails locally
    # (EMSGSIZE, surfaced to on_msgsize_error) or is dropped by the path —
    # either way the probe is simply never acknowledged.
    try:
        sock.setsockopt(_socket.IPPROTO_IP, _socket.IP_MTU_DISCOVER,
                        _socket.IP_PMTUDISC_DO)
    except (OSError, AttributeError):
        pass  # non-Linux: probing still converges, just without DF

_HDR = struct.Struct(">BQ")      # type, conn_id
_OFF = struct.Struct(">Q")       # stream offset / ack offset
_PLEN = struct.Struct(">I")      # probe datagram length
_ACK_DELAY = struct.Struct(">I")  # ACK-held time, microseconds (QUIC's
                                  # ack_delay: subtracted from RTT samples
                                  # so delayed ACKs don't inflate srtt and
                                  # spuriously activate pacing/RTO growth)

MTU_PAYLOAD = 1200               # conservative floor; fits any sane path MTU
_DATA_OVERHEAD = _HDR.size + _OFF.size
# probe total-datagram sizes, ascending; the largest PROBEACK'd one wins
PROBE_DATAGRAM_SIZES = (4096, 16384, 65000)
PROBE_ATTEMPTS = 3
PROBE_INTERVAL_S = 0.15
# EMSGSIZE within this window of a probe send is the probe itself bouncing
# off a smaller link (expected; the probed MTU was validated by PROBEACK) —
# outside it, it's the path shrinking under DATA and the MTU must clamp
PROBE_GRACE_S = 1.0
SEND_WINDOW_MAX = 2 * 1024 * 1024  # flow-control cap on unacked bytes
                                 # (kept under SOCK_BUF so one window
                                 # can never overflow the peer's kernel
                                 # buffer outright)
CWND_INITIAL_SEGS = 16           # initial congestion window (segments)
MIN_RTO_S = 0.2                  # RTO floor (srtt + 4*rttvar clamped here).
                                 # Generous on purpose: a same-process
                                 # receiver stalls its event loop tens of ms
                                 # on big memcpys/TLS records, and a floor
                                 # below that fires spurious RTOs that
                                 # collapse cwnd repeatedly (RFC 6298 uses
                                 # a 1 s floor; fast loss recovery is the
                                 # dup-ACK path's job, not the timer's)
PACE_SRTT_FLOOR_S = 0.005        # below this RTT pacing is a no-op (loopback)
ACK_DELAY_S = 0.02               # delayed-ACK timer (in-order data)
ACK_EVERY_BYTES = 64 * 1024      # ...or after this many unacked rx bytes
ACK_EVERY_DATAGRAMS = 2          # ...or every 2nd data datagram (QUIC's
                                 # max_ack_delay companion rule: keeps the
                                 # sender ACK-clocked during slow start
                                 # when datagrams are still MTU-small)
SOCK_BUF = 4 * 1024 * 1024       # kernel socket buffers (burst absorption)
DUP_ACK_FAST_RETX = 3            # NewReno-style fast retransmit threshold
RTO_BURST = 64                   # segments re-sent per RTO expiry
RTO_INITIAL_S = 0.2
RTO_MAX_S = 2.0
MAX_RETX = 12                    # ~20 s of backoff retries before declaring the peer dead
KEEPALIVE_S = 5.0                # parity: quinn keep_alive_interval 5 s
IDLE_TIMEOUT_S = 30.0
SOFT_CLOSE_WAIT_S = 3.0          # parity: quic.rs waits 3 s for `stopped`
_BOOTSTRAP = b"\x51"             # the single stream-opening byte


class _UdpStream(RawStream):
    """One reliable ordered stream over a datagram sender callable.

    ``send_packet(data)`` must transmit one UDP datagram to the peer.
    Incoming packets are fed by the owning endpoint via :meth:`on_packet`.
    """

    def __init__(self, conn_id: int, send_packet, on_closed=None):
        self._id = conn_id
        self._send_packet = send_packet
        self._on_closed = on_closed

        # send side
        self._next_off = 0                       # next byte offset to assign
        self._acked = 0                          # cumulative acked offset
        self._unacked: "Dict[int, list]" = {}    # off -> [payload, last_sent, retx]
        self._send_order: deque = deque()        # offsets in send order
        self._window_waiters: list = []
        self._fin_sent_off: Optional[int] = None
        self._finack = asyncio.Event()
        self._dup_acks = 0
        self._mtu = MTU_PAYLOAD                  # grows via path-MTU probing
        self._last_probe_sent = 0.0

        # congestion control: NewReno cwnd over the byte stream (the
        # reference inherits quinn's CC stack, quic.rs:37-146 — without
        # one, a static window floods lossy paths and collapses). Slow
        # start doubles per RTT until ssthresh; 3 dup-ACKs => halve +
        # fast recovery (dup-ACK inflation, partial-ACK retransmit); RTO
        # => back to 2 segments. RTO itself comes from an RFC 6298-style
        # srtt/rttvar estimator (Karn's rule: never sample retransmitted
        # segments), and writes are paced at ~1.25x cwnd/srtt so a whole
        # window never lands on the path as one burst.
        self._cwnd = float(CWND_INITIAL_SEGS * MTU_PAYLOAD)
        self._ssthresh = float("inf")
        self._in_recovery = False
        self._recover = 0                        # NewReno recovery point
        self._srtt: Optional[float] = None
        self._rttvar = 0.0
        self._min_rtt: Optional[float] = None  # true-path floor: immune to
                                               # scheduling-contention spikes
        self._pace_tokens = self._cwnd
        self._pace_last = time.monotonic()
        self._last_retx_t = 0.0   # RTT-sample epoch (Karn, strengthened)

        # receive side
        self._expected = 0
        self._ooo: Dict[int, bytes] = {}
        self._rbuf = bytearray()
        self._rbuf_wake = asyncio.Event()
        self._peer_fin: Optional[int] = None
        self._eof = False
        self._last_acked_rx = 0                  # _expected at last ACK sent
        self._ack_handle = None                  # pending delayed-ACK timer
        self._ack_pending_since = None           # arrival time of oldest
                                                 # in-order byte not yet ACKed
        self._rx_since_ack = 0                   # data datagrams since last ACK

        # batched-receive ACK coalescing (see begin/end_rx_batch): the
        # endpoint's drain loop brackets a whole socket backlog, and the
        # per-datagram ACK decisions collapse into one per wakeup
        self._rx_batched = False
        self._batch_ooo = 0
        self._batch_reack = False
        self._batch_progress = False

        self._error: Optional[Exception] = None
        self._closed = False
        self._last_recv = time.monotonic()
        self._rto = RTO_INITIAL_S
        self._timer = asyncio.create_task(self._timer_loop())
        self._prober = asyncio.create_task(self._probe_mtu())

    # -- packet ingress ------------------------------------------------------

    def on_packet(self, ptype: int, body: bytes) -> None:
        self._last_recv = time.monotonic()
        # UDP is the attack surface: a short/garbled datagram must be
        # DROPPED, never allowed to raise struct.error out of the
        # protocol callback (PROBE/PROBEACK bodies are already
        # length-guarded below; RST/FINACK/PING carry no body)
        if ptype in (_DATA, _ACK, _FIN) and len(body) < _OFF.size:
            return
        if ptype == _DATA:
            off = _OFF.unpack_from(body)[0]
            payload = body[_OFF.size:]
            if off < self._expected:
                # duplicate of delivered data: re-ACK immediately so a
                # retransmitting sender converges (once per batched drain)
                if self._rx_batched:
                    self._batch_reack = True
                else:
                    self._flush_ack()
            elif off == self._expected:
                # QUIC semantics: ack_delay is measured from the arrival
                # of the NEWEST data the ACK covers (the sender keys its
                # RTT sample to the newest acked segment), so overwrite on
                # every in-order arrival rather than set-once
                self._ack_pending_since = self._last_recv
                self._rbuf += payload
                self._expected += len(payload)
                while self._expected in self._ooo:
                    seg = self._ooo.pop(self._expected)
                    self._rbuf += seg
                    self._expected += len(seg)
                self._rbuf_wake.set()
                # in-order: delay the ACK — flushed by the QUIC-standard
                # every-2nd-datagram rule (keeps slow start ACK-clocked
                # while datagrams are small), the byte threshold (bounds
                # ACK latency once MTU probing makes datagrams huge), or
                # the timer. Inside a batched drain the decision defers
                # to end_rx_batch: one coalesced ACK per socket wakeup.
                self._rx_since_ack += 1
                if self._rx_batched:
                    self._batch_progress = True
                elif (self._rx_since_ack >= ACK_EVERY_DATAGRAMS
                        or self._expected - self._last_acked_rx
                        >= ACK_EVERY_BYTES):
                    self._flush_ack()
                else:
                    self._schedule_ack()
            else:
                self._ooo.setdefault(off, payload)
                # out-of-order: ACK immediately; the duplicate cumulative
                # ACKs drive the sender's fast retransmit (batched drains
                # coalesce but preserve the dup count, capped — see
                # end_rx_batch)
                if self._rx_batched:
                    self._batch_ooo += 1
                else:
                    self._flush_ack()
            self._check_eof()
        elif ptype == _PROBE:
            # the datagram made it across the path — confirm its size, but
            # only if the claimed length matches what actually arrived
            if len(body) >= _PLEN.size:
                (plen,) = _PLEN.unpack_from(body)
                if plen == _HDR.size + len(body):
                    self._tx(_PROBEACK, _PLEN.pack(plen))
        elif ptype == _PROBEACK:
            # accept only sizes we genuinely probe with — an arbitrary
            # peer-supplied length could push _mtu past what sendto allows
            if len(body) >= _PLEN.size:
                (plen,) = _PLEN.unpack_from(body)
                if plen in PROBE_DATAGRAM_SIZES:
                    new_mtu = max(self._mtu, plen - _DATA_OVERHEAD)
                    if new_mtu > self._mtu and self._ssthresh == float("inf"):
                        # cwnd is segment-denominated CC state expressed in
                        # bytes; a probed-up path just redefined "segment".
                        # Before any loss evidence (ssthresh untouched),
                        # re-express the window in the new units — else a
                        # 64 KB-MTU path ramps from a 1200 B-era window
                        # through queue-bloated RTTs, and short flows
                        # measure the ramp instead of the path. CAPPED at
                        # 4x the current window per probe step: one
                        # PROBEACK is one delivery proof at the new size,
                        # not license to dump CWND_INITIAL_SEGS jumbo
                        # segments on a shallow-buffered path in a single
                        # burst — the ascending probe ladder re-expresses
                        # in <=4x steps and still reaches the full window
                        # on paths that confirm every size. Pacing still
                        # smooths the larger window onto the wire.
                        self._cwnd = max(self._cwnd, min(
                            float(CWND_INITIAL_SEGS * new_mtu),
                            4.0 * self._cwnd))
                    self._mtu = new_mtu
        elif ptype == _ACK:
            ack = _OFF.unpack_from(body)[0]
            ack_delay_s = 0.0
            if len(body) >= _OFF.size + _ACK_DELAY.size:
                # clamp to what a well-behaved peer can legitimately hold
                # (timer + scheduling slack — QUIC's max_ack_delay clamp):
                # an inflated field must not pin min_rtt/srtt to the floor
                ack_delay_s = min(
                    _ACK_DELAY.unpack_from(body, _OFF.size)[0] / 1e6,
                    2.0 * ACK_DELAY_S)
            now = time.monotonic()
            if ack > self._acked:
                newly = ack - self._acked
                self._acked = ack
                self._dup_acks = 0
                rtt_sample = None
                while self._send_order:
                    off = self._send_order[0]
                    seg = self._unacked.get(off)
                    if seg is None or off + len(seg[0]) > ack:
                        break
                    # Karn, strengthened: never-retransmitted AND sent
                    # after the last loss event — a segment that sat in
                    # the queue behind a repair measures sojourn, not RTT
                    if seg[2] == 0 and seg[1] > self._last_retx_t:
                        rtt_sample = now - seg[1]
                    self._send_order.popleft()
                    self._unacked.pop(off, None)
                if rtt_sample is not None:
                    # QUIC semantics: the peer held this ACK (delayed-ACK
                    # timer / byte threshold); that hold time is not path
                    # RTT. min_rtt takes the RAW sample (RFC 9002 §5.2):
                    # it gates pacing, and an unauthenticated
                    # peer-reported delay must not be able to deflate it.
                    # The adjusted sample floors at min_rtt (§5.3), so a
                    # maxed-out delay stamp can't drag srtt below the
                    # path's observed floor either.
                    floor = self._min_rtt if self._min_rtt is not None \
                        else 5e-5
                    floor = min(floor, rtt_sample)
                    self._rtt_update(
                        max(rtt_sample - ack_delay_s, floor, 5e-5),
                        raw_sample=rtt_sample)
                if self._in_recovery:
                    if ack >= self._recover:
                        # full recovery: deflate to ssthresh
                        self._in_recovery = False
                        self._cwnd = max(self._ssthresh, 2.0 * self._mtu)
                    elif self._send_order:
                        # partial ACK: the next hole is also lost —
                        # retransmit it now and DEFLATE by the data the
                        # ACK took out of flight, plus one segment
                        # (RFC 6582 §3.2: without this, every partial
                        # ACK releases a fresh burst into the congested
                        # path on top of the retransmit)
                        self._cwnd = max(self._cwnd - newly + self._mtu,
                                         2.0 * self._mtu)
                        off = self._send_order[0]
                        seg = self._unacked.get(off)
                        if seg is not None:
                            seg[1] = now
                            seg[2] += 1
                            self._last_retx_t = now
                            self._tx(_DATA, _OFF.pack(off) + seg[0])
                elif self._cwnd < self._ssthresh:
                    self._cwnd += newly                       # slow start
                else:                                         # avoidance
                    self._cwnd += self._mtu * newly / self._cwnd
                if not self._in_recovery and self._send_order:
                    # ACK-clocked repair: an RTO-stale front hole is
                    # resent NOW instead of waiting for the next 50 ms
                    # timer tick — this is what drains a multi-hole
                    # window at ACK speed after a burst loss
                    off = self._send_order[0]
                    seg = self._unacked.get(off)
                    if seg is not None and now - seg[1] >= self._rto:
                        seg[1] = now
                        seg[2] += 1
                        self._last_retx_t = now
                        self._tx(_DATA, _OFF.pack(off) + seg[0])
                self._wake_window()
            elif ack == self._acked and self._send_order:
                # duplicate ACK: the peer is holding out-of-order data past a
                # hole — fast-retransmit the earliest unacked segment and
                # enter fast recovery (halve the window once per loss event)
                self._dup_acks += 1
                if self._in_recovery:
                    self._cwnd += self._mtu   # dup-ACK inflation
                    self._wake_window()
                elif self._dup_acks >= DUP_ACK_FAST_RETX:
                    self._dup_acks = 0
                    self._in_recovery = True
                    self._recover = self._next_off
                    self._ssthresh = max(self._inflight() / 2.0,
                                         2.0 * self._mtu)
                    self._cwnd = self._ssthresh + 3.0 * self._mtu
                    off = self._send_order[0]
                    seg = self._unacked.get(off)
                    if seg is not None:
                        seg[1] = now
                        seg[2] += 1
                        self._last_retx_t = now
                        self._tx(_DATA, _OFF.pack(off) + seg[0])
        elif ptype == _FIN:
            self._peer_fin = _OFF.unpack_from(body)[0]
            self._flush_ack()
            self._tx(_FINACK, b"")
            self._check_eof()
        elif ptype == _FINACK:
            self._finack.set()
        elif ptype == _PING:
            pass  # any packet refreshes last_recv
        elif ptype == _RST:
            self._poison(ConnectionResetError("peer reset the connection"))

    def _check_eof(self) -> None:
        if self._peer_fin is not None and self._expected >= self._peer_fin:
            self._eof = True
            self._rbuf_wake.set()

    # -- delayed ACKs --------------------------------------------------------

    def _ack_delay_us(self) -> int:
        """Time this ACK's newest-covered data sat waiting, microseconds."""
        since, self._ack_pending_since = self._ack_pending_since, None
        if since is None:
            return 0
        held = time.monotonic() - since
        return min(0xFFFFFFFF, max(0, int(held * 1e6)))

    def _flush_ack(self) -> None:
        if self._ack_handle is not None:
            self._ack_handle.cancel()
            self._ack_handle = None
        self._last_acked_rx = self._expected
        self._rx_since_ack = 0
        self._tx(_ACK, _OFF.pack(self._expected)
                 + _ACK_DELAY.pack(self._ack_delay_us()))

    def begin_rx_batch(self) -> None:
        """Enter batched-receive mode for one endpoint drain: per-datagram
        ACK decisions defer to :meth:`end_rx_batch` so a whole socket
        backlog generates ONE coalesced ACK instead of one per datagram
        (the unbatched per-packet rules still apply outside a drain —
        tests and exotic endpoints feed ``on_packet`` directly)."""
        self._rx_batched = True
        self._batch_ooo = 0
        self._batch_reack = False
        self._batch_progress = False

    def end_rx_batch(self) -> None:
        """Emit the batch's coalesced ACK decision."""
        self._rx_batched = False
        if self._closed:
            return
        if self._batch_ooo:
            # A hole is outstanding past delivered data: send the
            # cumulative ACK, duplicated up to the fast-retransmit
            # threshold so the sender's dup-ACK clocking sees the same
            # evidence the per-datagram path produced (each OOO datagram
            # used to emit one) without re-ACKing a 64-datagram burst
            # 64 times. When the same drain ALSO advanced _expected, the
            # cumulative ACK reads as progress at the sender — not a
            # duplicate — so it doesn't count toward the threshold and
            # the full dup count follows it; otherwise the cumulative
            # ACK itself is the first duplicate.
            dups = min(self._batch_ooo, DUP_ACK_FAST_RETX)
            if not self._batch_progress:
                dups -= 1
            self._flush_ack()
            for _ in range(dups):
                self._tx(_ACK, _OFF.pack(self._expected)
                         + _ACK_DELAY.pack(0))
        elif self._batch_progress:
            if (self._rx_since_ack >= ACK_EVERY_DATAGRAMS
                    or self._expected - self._last_acked_rx
                    >= ACK_EVERY_BYTES):
                self._flush_ack()
            else:
                self._schedule_ack()
        elif self._batch_reack:
            self._flush_ack()

    def _schedule_ack(self) -> None:
        if self._ack_handle is None:
            self._ack_handle = asyncio.get_running_loop().call_later(
                ACK_DELAY_S, self._delayed_ack_fire)

    def _delayed_ack_fire(self) -> None:
        self._ack_handle = None
        if not self._closed:
            self._flush_ack()

    # -- packet egress -------------------------------------------------------

    def _tx(self, ptype: int, body: bytes) -> None:
        try:
            self._send_packet(_HDR.pack(ptype, self._id) + body)
        except Exception:
            pass  # datagram sends are best-effort; the timer retransmits

    def _wake_window(self) -> None:
        waiters, self._window_waiters = self._window_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def _inflight(self) -> int:
        return self._next_off - self._acked

    # -- congestion control --------------------------------------------------

    def _rtt_update(self, sample: float,
                    raw_sample: Optional[float] = None) -> None:
        """RFC 6298 srtt/rttvar; RTO = srtt + 4*rttvar, clamped. min_rtt
        ratchets on the RAW (ack_delay-unadjusted) sample per RFC 9002
        §5.2 — it gates pacing, so peer-reported delay must not move it."""
        raw = sample if raw_sample is None else raw_sample
        if self._min_rtt is None or raw < self._min_rtt:
            self._min_rtt = raw
        if self._srtt is None:
            self._srtt = sample
            self._rttvar = sample / 2.0
        else:
            self._rttvar = 0.75 * self._rttvar + \
                0.25 * abs(self._srtt - sample)
            self._srtt = 0.875 * self._srtt + 0.125 * sample
        self._rto = min(max(MIN_RTO_S, self._srtt + 4.0 * self._rttvar),
                        RTO_MAX_S)

    def _window(self) -> float:
        """Effective send window: congestion-bound, flow-capped."""
        return min(SEND_WINDOW_MAX, max(self._cwnd, 2.0 * self._mtu))

    async def _pace(self, nbytes: int) -> None:
        """Token-bucket pacing at ~1.25x cwnd/srtt (burst cap = one cwnd).
        The on/off gate uses MIN RTT, not srtt: srtt absorbs receiver
        scheduling stalls (single-process peers share one event loop), and
        a contaminated estimate must not switch pacing on over a path
        whose true RTT is loopback-fast — each pace sleep costs ~1 ms of
        timer granularity per segment."""
        srtt = self._srtt
        if srtt is None or srtt <= PACE_SRTT_FLOOR_S:
            return
        if self._min_rtt is not None and self._min_rtt <= PACE_SRTT_FLOOR_S:
            return
        rate = 1.25 * max(self._cwnd, 2.0 * self._mtu) / srtt
        # burst cap must cover at least one segment: a probed-up MTU can
        # exceed a freshly-started cwnd, and a cwnd-only cap would make
        # the bucket unfillable (pace deadlock)
        cap = max(self._cwnd, 2.0 * self._mtu, float(nbytes))
        now = time.monotonic()
        self._pace_tokens = min(
            cap, self._pace_tokens + (now - self._pace_last) * rate)
        self._pace_last = now
        while self._pace_tokens < nbytes and self._error is None \
                and not self._closed:
            await asyncio.sleep(min(0.01, (nbytes - self._pace_tokens) / rate))
            now = time.monotonic()
            self._pace_tokens = min(
                cap, self._pace_tokens + (now - self._pace_last) * rate)
            self._pace_last = now
        self._pace_tokens -= nbytes

    # -- path-MTU probing ----------------------------------------------------

    async def _probe_mtu(self) -> None:
        """DPLPMTUD-lite: pad datagrams to candidate sizes; the peer
        PROBEACKs whatever actually arrives. Lost probes (path too small)
        simply never raise ``_mtu``. Runs once per connection."""
        try:
            for attempt in range(PROBE_ATTEMPTS):
                if attempt:  # first burst goes out immediately (RFC 8899
                    await asyncio.sleep(PROBE_INTERVAL_S)  # probes on
                if self._closed or self._error is not None:  # confirmation)
                    return
                top = PROBE_DATAGRAM_SIZES[-1]
                if self._mtu >= top - _DATA_OVERHEAD:
                    return
                for size in PROBE_DATAGRAM_SIZES:
                    if size - _DATA_OVERHEAD <= self._mtu:
                        continue
                    pad = size - _HDR.size - _PLEN.size
                    self._last_probe_sent = time.monotonic()
                    self._tx(_PROBE, _PLEN.pack(size) + b"\x00" * pad)
        except asyncio.CancelledError:
            pass

    def on_msgsize_error(self) -> None:
        """A DF-bit datagram bounced (local EMSGSIZE or ICMP frag-needed).

        Within the probe grace window this is an oversized PROBE being
        rejected — expected, ignore (any _mtu growth was validated by a
        PROBEACK that actually crossed the path). Otherwise the path
        shrank under DATA: clamp to the floor AND re-segment unacked data,
        because retransmissions resend stored segments verbatim and an
        oversized one would bounce forever until MAX_RETX poisoned the
        stream."""
        if time.monotonic() - self._last_probe_sent < PROBE_GRACE_S:
            return
        if not any(len(s[0]) > MTU_PAYLOAD for s in self._unacked.values()):
            # nothing of OURS bigger than the floor is in flight, so this
            # bounce can't be our DATA (a bounced segment stays unacked) —
            # it's another stream's probe on a shared server socket, or a
            # stale ICMP. Don't punish this stream for it.
            return
        self._mtu = MTU_PAYLOAD
        resplit: Dict[int, list] = {}
        order = []
        for off in sorted(self._unacked):
            seg, _last_sent, retx = self._unacked[off]
            for j in range(0, max(len(seg), 1), MTU_PAYLOAD):
                # last_sent=0 ⇒ the RTO path re-sends the refitted
                # segments promptly
                resplit[off + j] = [seg[j:j + MTU_PAYLOAD], 0.0, retx]
                order.append(off + j)
        self._unacked = resplit
        self._send_order = deque(order)
        # the clamp may be a misattribution (shared socket) or the path
        # may recover: restart the one-shot prober so a still-jumbo path
        # re-grows within ~half a second instead of being floored forever
        if self._prober.done():
            self._prober = asyncio.create_task(self._probe_mtu())

    # -- timers --------------------------------------------------------------

    async def _timer_loop(self) -> None:
        last_ping = time.monotonic()
        try:
            while not self._closed and self._error is None:
                await asyncio.sleep(0.05)
                now = time.monotonic()
                # RTO expiry on the earliest unacked segment: the whole
                # window may be lost — re-send a burst from the front
                if self._send_order:
                    off = self._send_order[0]
                    seg = self._unacked.get(off)
                    if seg is not None and now - seg[1] >= self._rto:
                        seg[2] += 1
                        if seg[2] > MAX_RETX:
                            self._poison(TimeoutError(
                                f"segment @{off} unacked after {MAX_RETX} "
                                "retransmits"))
                            return
                        self._rto = min(self._rto * 2, RTO_MAX_S)
                        # congestion response to a timeout: whole-window
                        # loss — collapse to 2 segments, re-enter slow
                        # start toward half the flight size
                        self._ssthresh = max(self._inflight() / 2.0,
                                             2.0 * self._mtu)
                        self._cwnd = 2.0 * self._mtu
                        self._in_recovery = False
                        # resend at most one (new) cwnd worth from the
                        # front — the burst cap a static count can't give
                        budget = max(int(self._cwnd), 2 * MTU_PAYLOAD)
                        self._last_retx_t = now
                        for o in islice(self._send_order, RTO_BURST):
                            s = self._unacked.get(o)
                            if s is not None:
                                if budget <= 0:
                                    break
                                budget -= len(s[0])
                                s[1] = now
                                self._tx(_DATA, _OFF.pack(o) + s[0])
                # FIN retransmission until FINACK
                if self._fin_sent_off is not None and not self._finack.is_set():
                    self._tx(_FIN, _OFF.pack(self._fin_sent_off))
                if now - last_ping >= KEEPALIVE_S:
                    last_ping = now
                    self._tx(_PING, b"")
                if now - self._last_recv > IDLE_TIMEOUT_S:
                    self._poison(TimeoutError("idle timeout"))
                    return
        except asyncio.CancelledError:
            pass

    def _poison(self, exc: Exception) -> None:
        if self._error is None:
            self._error = exc
        self._rbuf_wake.set()
        self._wake_window()
        if self._on_closed is not None:
            try:
                self._on_closed(self._id)
            except Exception:
                pass

    # -- RawStream interface -------------------------------------------------

    async def read_exactly(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            if self._error is not None:
                raise self._error
            if self._eof:
                raise asyncio.IncompleteReadError(bytes(self._rbuf), n)
            self._rbuf_wake.clear()
            await self._rbuf_wake.wait()
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    async def read_some(self, max_n: int) -> bytes:
        while not self._rbuf:
            if self._error is not None:
                raise self._error
            if self._eof:
                raise asyncio.IncompleteReadError(b"", 1)
            self._rbuf_wake.clear()
            await self._rbuf_wake.wait()
        take = min(max_n, len(self._rbuf))
        out = bytes(self._rbuf[:take])
        del self._rbuf[:take]
        return out

    async def write(self, data) -> None:
        if self._error is not None:
            raise self._error
        if self._fin_sent_off is not None:
            raise ConnectionError("write after close")
        view = memoryview(bytes(data) if isinstance(data, (bytearray, memoryview)) else data)
        i = 0
        n = len(view)
        burst = 0
        while i < n:
            if burst >= 128 * 1024:
                # yield between bursts: lets a same-event-loop peer (and
                # our own ACK processing) run; without it one write could
                # emit a full window before any datagram is consumed
                burst = 0
                await asyncio.sleep(0)
            while self._inflight() >= self._window():
                if self._error is not None:
                    raise self._error
                fut = asyncio.get_running_loop().create_future()
                self._window_waiters.append(fut)
                await fut
            # read the MTU only after the window wait: it tracks the probed
            # path (grows mid-write) and may have been CLAMPED while we
            # were blocked — cutting with a stale larger value would emit a
            # segment that bounces off the shrunken path forever
            mtu = self._mtu
            seg = bytes(view[i:i + mtu])
            await self._pace(len(seg))
            if self._error is not None:
                raise self._error
            i += len(seg)
            burst += len(seg)
            off = self._next_off
            self._next_off += len(seg)
            self._unacked[off] = [seg, time.monotonic(), 0]
            self._send_order.append(off)
            self._tx(_DATA, _OFF.pack(off) + seg)

    async def close(self) -> None:
        """Graceful finish: wait for all data to be acked, send FIN, wait
        for FINACK — bounded by SOFT_CLOSE_WAIT_S (parity quic.rs 3 s)."""
        if self._error is not None or self._closed:
            self.abort()
            return
        deadline = time.monotonic() + SOFT_CLOSE_WAIT_S
        while self._send_order and time.monotonic() < deadline \
                and self._error is None:
            await asyncio.sleep(0.02)
        self._fin_sent_off = self._next_off
        self._tx(_FIN, _OFF.pack(self._fin_sent_off))
        try:
            # keep a minimum FINACK window even when draining consumed the
            # deadline: the timer loop retransmits the FIN during this wait,
            # so a single lost FIN datagram doesn't leave the peer hanging
            # until its idle timeout
            remaining = max(0.3, deadline - time.monotonic())
            async with asyncio.timeout(remaining):
                await self._finack.wait()
        except asyncio.TimeoutError:
            pass
        self.abort(send_rst=False)

    def abort(self, send_rst: bool = True) -> None:
        if not self._closed:
            self._closed = True
            if send_rst and self._error is None:
                self._tx(_RST, b"")
        self._timer.cancel()
        self._prober.cancel()
        if self._ack_handle is not None:
            self._ack_handle.cancel()
            self._ack_handle = None
        if self._error is None:
            self._error = ConnectionError("connection closed")
        self._rbuf_wake.set()
        self._wake_window()
        if self._on_closed is not None:
            try:
                self._on_closed(self._id)
            except Exception:
                pass


# One endpoint wakeup drains this many datagrams before yielding back to
# the event loop (level-triggered readiness re-fires if more remain). The
# old one-callback-per-datagram shape paid a full event-loop round trip +
# recvfrom per packet; a drained batch shares one wakeup, and every
# touched stream emits ONE coalesced ACK at the end.
_RX_BATCH = 128
_RX_BUF_BYTES = 65536 + 128  # one max datagram + header slack


class _FallbackDatagramProtocol(asyncio.DatagramProtocol):
    """Per-datagram dispatch shim for event loops without ``add_reader``:
    feeds the owning endpoint's ``_dispatch`` exactly like the batched
    drain does, one datagram per batch bracket (the coalesced-ACK
    machinery still runs, it just never sees more than one datagram per
    'drain'). Errors route to the endpoint's ``_on_sock_error`` — the old
    ``error_received`` semantics."""

    def __init__(self, endpoint: "_UdpEndpoint"):
        self._endpoint = endpoint

    def datagram_received(self, data: bytes, addr) -> None:
        ep = self._endpoint
        if ep._closed or len(data) < _HDR.size:
            return
        ptype, conn_id = _HDR.unpack_from(data)
        touched: dict = {}
        try:
            ep._dispatch(ptype, conn_id, data[_HDR.size:], addr, touched)
        finally:
            for stream in touched.values():
                stream.end_rx_batch()

    def error_received(self, exc: OSError) -> None:
        self._endpoint._on_sock_error(exc)


class _UdpEndpoint:
    """Manual non-blocking UDP socket with a batched receive drain.

    Replaces the asyncio ``DatagramProtocol`` plumbing: readiness fires
    ``_on_readable`` once per backlog, which drains up to ``_RX_BATCH``
    datagrams with ``recvfrom_into`` into one reusable buffer, dispatches
    each, and then lets every touched stream collapse its ACK decisions
    into a single coalesced ACK (begin/end_rx_batch). Sends are direct
    (synchronous) ``sendto``/``send`` — EMSGSIZE attributes to the exact
    stream that sent, kernel-full drops are best-effort (the ARQ
    retransmits), matching the old error_received semantics without the
    transport indirection."""

    def __init__(self, sock, loop):
        self.sock = sock
        self._loop = loop
        self._fd = sock.fileno()
        self._closed = False
        self._rx_buf = bytearray(_RX_BUF_BYTES)
        self._rx_view = memoryview(self._rx_buf)
        # datagram-endpoint fallback transport for loops without a
        # readiness API (Windows ProactorEventLoop raises
        # NotImplementedError from add_reader); created by the async
        # ``ensure_transport`` since __init__ can't await
        self._transport = None
        try:
            loop.add_reader(self._fd, self._on_readable)
            self._reader_attached = True
        except NotImplementedError:
            self._reader_attached = False

    async def ensure_transport(self) -> None:
        """Attach the ``create_datagram_endpoint`` fallback when the loop
        rejected ``add_reader``. One warning line: the batched-recv drain
        (and its ACK coalescing) degrades to per-datagram dispatch."""
        if self._reader_attached or self._transport is not None:
            return
        logger.warning(
            "event loop %s has no add_reader (proactor?); QUIC endpoint "
            "falling back to the datagram-endpoint path (per-datagram "
            "dispatch, no batched receive drain)",
            type(self._loop).__name__)
        self._transport, _ = await self._loop.create_datagram_endpoint(
            lambda: _FallbackDatagramProtocol(self), sock=self.sock)

    # subclasses: dispatch one datagram (header already length-checked)
    def _dispatch(self, ptype: int, conn_id: int, body: bytes, addr,
                  touched: dict) -> None:
        raise NotImplementedError

    def _on_sock_error(self, exc: OSError) -> None:
        raise NotImplementedError

    def _on_readable(self) -> None:
        sock = self.sock
        buf = self._rx_buf
        view = self._rx_view
        touched: dict = {}
        try:
            for _ in range(_RX_BATCH):
                try:
                    nbytes, addr = sock.recvfrom_into(buf)
                except (BlockingIOError, InterruptedError):
                    break
                except OSError as exc:
                    self._on_sock_error(exc)
                    break
                if nbytes < _HDR.size:
                    continue  # short datagram: attack surface, drop
                ptype, conn_id = _HDR.unpack_from(buf)
                self._dispatch(ptype, conn_id,
                               bytes(view[_HDR.size:nbytes]), addr, touched)
        finally:
            # every touched stream settles its coalesced ACK AFTER the
            # whole drain (and before any timer gets to run)
            for stream in touched.values():
                stream.end_rx_batch()

    @staticmethod
    def _enter_batch(stream: "_UdpStream", touched: dict) -> None:
        key = id(stream)
        if key not in touched:
            touched[key] = stream
            stream.begin_rx_batch()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._reader_attached:
            try:
                self._loop.remove_reader(self._fd)
            except Exception:
                pass
        if self._transport is not None:
            try:
                self._transport.close()  # closes the socket it owns
            except Exception:
                pass
        try:
            self.sock.close()
        except OSError:
            pass


class _ClientEndpoint(_UdpEndpoint):
    """One UDP socket per outbound connection (connected to the server)."""

    def __init__(self, sock, loop):
        super().__init__(sock, loop)
        self.stream: Optional[_UdpStream] = None
        self.synack = loop.create_future()

    def send(self, pkt: bytes) -> None:
        if self._transport is not None:  # datagram-endpoint fallback
            try:
                self._transport.sendto(pkt)  # connected socket: no addr
            except Exception:
                pass  # errors surface via error_received
            return
        try:
            self.sock.send(pkt)
        except (BlockingIOError, InterruptedError):
            pass  # kernel buffer full: best-effort drop, ARQ retransmits
        except OSError as exc:
            if exc.errno == errno.EMSGSIZE:
                # DF-bit datagram exceeded the path (RFC 8899); the stream
                # decides probe-bounce vs genuine path-MTU decrease. Never
                # poison for it — that would kill every connection on real
                # non-loopback paths ~150 ms after connect when probing
                # starts.
                if self.stream is not None:
                    self.stream.on_msgsize_error()
            elif self.stream is not None:
                # any other send error on the connected socket (refused,
                # net/host unreachable, EPERM...) poisons immediately —
                # the old DatagramTransport error_received semantics; a
                # dead route must fail the link now, not at IDLE_TIMEOUT
                self.stream._poison(exc)

    def _dispatch(self, ptype, conn_id, body, addr, touched) -> None:
        if ptype == _SYNACK:
            if not self.synack.done():
                self.synack.set_result(conn_id)
            return
        stream = self.stream
        if stream is not None and conn_id == stream._id:
            self._enter_batch(stream, touched)
            stream.on_packet(ptype, body)

    def _on_sock_error(self, exc: OSError) -> None:
        # a connected UDP socket surfaces ICMP errors on recv
        if exc.errno == errno.EMSGSIZE:
            if self.stream is not None:
                self.stream.on_msgsize_error()
            return
        if self.stream is not None:
            self.stream._poison(exc)


class _ServerEndpoint(_UdpEndpoint):
    """The listener's single UDP socket, demuxing by connection id."""

    def __init__(self, sock, loop, listener: "QuicListener"):
        super().__init__(sock, loop)
        self.listener = listener
        self.streams: Dict[int, _UdpStream] = {}
        self.addrs: Dict[int, Tuple] = {}

    def _sendto(self, pkt: bytes, addr, conn_id: int) -> None:
        if self._transport is not None:  # datagram-endpoint fallback
            try:
                self._transport.sendto(pkt, addr)
            except Exception:
                pass  # errors surface via error_received (broadcast EMSGSIZE)
            return
        try:
            self.sock.sendto(pkt, addr)
        except (BlockingIOError, InterruptedError):
            pass  # best-effort; ARQ retransmits
        except OSError as exc:
            if exc.errno == errno.EMSGSIZE:
                # synchronous sendto attributes the bounce to the exact
                # stream that sent (the old async error_received had to
                # broadcast it to every stream on the shared socket)
                stream = self.streams.get(conn_id)
                if stream is not None:
                    stream.on_msgsize_error()
            # other errors: drop; per-stream timers decide

    def _sender_for(self, conn_id: int):
        def send(pkt: bytes) -> None:
            addr = self.addrs.get(conn_id)
            if addr is not None and not self._closed:
                self._sendto(pkt, addr, conn_id)
        return send

    def _dispatch(self, ptype, conn_id, body, addr, touched) -> None:
        if ptype == _SYN:
            known = conn_id in self.streams
            if not known and not self.listener._closed:
                send = self._sender_for(conn_id)
                stream = _UdpStream(conn_id, send, on_closed=self._drop)
                self.streams[conn_id] = stream
                self.addrs[conn_id] = addr
                self.listener._accept_q.put_nowait(
                    _QuicUnfinalized(stream, self.listener._ssl_context))
            # (re-)ack the SYN — the client retries until it sees this
            if conn_id in self.streams or known:
                self.addrs[conn_id] = addr
                self._sendto(_HDR.pack(_SYNACK, conn_id), addr, conn_id)
            return
        stream = self.streams.get(conn_id)
        if stream is not None:
            self.addrs[conn_id] = addr  # follow NAT rebinding
            self._enter_batch(stream, touched)
            stream.on_packet(ptype, body)

    def _drop(self, conn_id: int) -> None:
        self.streams.pop(conn_id, None)
        self.addrs.pop(conn_id, None)

    def _on_sock_error(self, exc: OSError) -> None:
        # recv-side ICMP on the shared socket names no peer: EMSGSIZE goes
        # to every stream (each ignores it while its own prober is
        # active); anything else is dropped — per-stream timers decide
        if exc.errno == errno.EMSGSIZE:
            for stream in list(self.streams.values()):
                stream.on_msgsize_error()


class _QuicUnfinalized(UnfinalizedConnection):
    def __init__(self, stream: _UdpStream, ssl_context: ssl.SSLContext):
        self._stream = stream
        self._ssl_context = ssl_context

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        # TLS handshake over the ARQ stream, then consume the client's
        # stream-bootstrap byte — encrypted, like quinn's stream open rides
        # the secured connection (parity quic.rs:224-266)
        try:
            async with asyncio.timeout(CONNECT_TIMEOUT_S):
                tls = await TlsStream.wrap_server(self._stream,
                                                  self._ssl_context)
                boot = await tls.read_exactly(1)
        except (ssl.SSLError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, ConnectionError) as exc:
            self._stream.abort()
            bail(ErrorKind.CONNECTION, "QUIC TLS handshake failed", exc)
        if boot != _BOOTSTRAP:
            self._stream.abort()
            bail(ErrorKind.CONNECTION, "bad QUIC stream bootstrap byte")
        return Connection(tls, limiter, label="quic")


class QuicListener(Listener):
    def __init__(self):
        self._accept_q: asyncio.Queue = asyncio.Queue()
        self._endpoint: Optional[_ServerEndpoint] = None
        self._ssl_context: Optional[ssl.SSLContext] = None
        self._closed = False
        self.bound_port: int = 0

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        item = await self._accept_q.get()
        if item is None:
            bail(ErrorKind.CONNECTION, "listener closed")
        return item

    async def close(self) -> None:
        self._closed = True
        if self._endpoint is not None:
            for stream in list(self._endpoint.streams.values()):
                stream.abort()
            self._endpoint.close()
        self._accept_q.put_nowait(None)


class Quic(Protocol):
    name = "quic"

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        host, port = parse_endpoint(endpoint)
        # resolve the trust root BEFORE any socket/stream exists: a broken
        # CA configuration bails (typed, fatal) without leaking timer tasks
        ctx, server_hostname = client_context_for(use_local_authority, host)
        loop = asyncio.get_running_loop()
        import socket as _socket
        try:
            infos = await loop.getaddrinfo(host, port,
                                           type=_socket.SOCK_DGRAM)
            # non-blocking UDP connect is immediate; try every resolved
            # family in order (v6-first hostname on a v6-less host must
            # fall through to its A record)
            sock = _socket_for_first_usable(
                infos, lambda s, addr: s.connect(addr))
        except OSError as exc:
            bail(ErrorKind.CONNECTION, f"quic connect to {endpoint} failed", exc)
        proto = _ClientEndpoint(sock, loop)
        try:
            await proto.ensure_transport()
        except Exception as exc:
            proto.close()
            bail(ErrorKind.CONNECTION,
                 f"quic endpoint setup for {endpoint} failed", exc)

        conn_id = int.from_bytes(os.urandom(8), "big")
        syn = _HDR.pack(_SYN, conn_id)
        try:
            deadline = time.monotonic() + CONNECT_TIMEOUT_S
            while True:
                proto.send(syn)
                try:
                    async with asyncio.timeout(
                            min(0.2, max(0.01, deadline - time.monotonic()))):
                        got = await asyncio.shield(proto.synack)
                        if got == conn_id:
                            break
                        bail(ErrorKind.CONNECTION, "SYNACK for wrong connection")
                except asyncio.TimeoutError:
                    if time.monotonic() >= deadline:
                        bail(ErrorKind.CONNECTION,
                             f"quic connect to {endpoint} timed out")
        except BaseException:
            proto.close()
            raise

        stream = _UdpStream(conn_id, proto.send,
                            on_closed=lambda _id: proto.close())
        proto.stream = stream
        try:
            async with asyncio.timeout(CONNECT_TIMEOUT_S):
                # TLS 1.3 over the ARQ stream (parity quinn+rustls), then
                # open "the one bidirectional stream" with the bootstrap
                # byte — inside TLS
                tls = await TlsStream.wrap_client(stream, ctx,
                                                  server_hostname)
                await tls.write(_BOOTSTRAP)
        except (ssl.SSLError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, ConnectionError) as exc:
            stream.abort()
            bail(ErrorKind.CONNECTION,
                 f"quic TLS handshake with {endpoint} failed", exc)
        return Connection(tls, limiter, label=f"quic:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str,
                   certificate: Optional[Certificate] = None,
                   reuse_port: bool = False) -> Listener:
        if reuse_port:
            bail(ErrorKind.CONNECTION,
                 "quic sharding via SO_REUSEPORT is not supported yet "
                 "(connection IDs would need kernel steering); run "
                 "--shards with a TCP user transport")
        host, port = parse_endpoint(endpoint)
        if certificate is None:
            certificate = local_certificate()
        loop = asyncio.get_running_loop()
        listener = QuicListener()
        listener._ssl_context = certificate.server_context()
        import socket as _socket
        try:
            infos = await loop.getaddrinfo(host, port,
                                           type=_socket.SOCK_DGRAM,
                                           flags=_socket.AI_PASSIVE)
            sock = _socket_for_first_usable(
                infos, lambda s, addr: s.bind(addr))
        except OSError as exc:
            bail(ErrorKind.CONNECTION, f"quic bind to {endpoint} failed", exc)
        listener._endpoint = _ServerEndpoint(sock, loop, listener)
        try:
            await listener._endpoint.ensure_transport()
        except Exception as exc:
            listener._endpoint.close()
            bail(ErrorKind.CONNECTION,
                 f"quic endpoint setup for {endpoint} failed", exc)
        listener.bound_port = sock.getsockname()[1]
        return listener
