"""Plain TCP transport.

Capability parity with cdn-proto/src/connection/protocols/tcp.rs:37-173:
TCP_NODELAY on both sides, 5 s connect timeout, u32 length-delimited frames
(framing lives in transport.base).
"""

from __future__ import annotations

import asyncio
import socket

from pushcdn_tpu.proto.error import Error, ErrorKind, bail
from pushcdn_tpu.proto.limiter import Limiter, NO_LIMIT
from pushcdn_tpu.proto.error import parse_endpoint
from pushcdn_tpu.proto.transport.base import (
    CONNECT_TIMEOUT_S,
    AsyncioStream,
    Connection,
    Listener,
    Protocol,
    UnfinalizedConnection,
)


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass


class _TcpUnfinalized(UnfinalizedConnection):
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._reader, self._writer = reader, writer

    async def finalize(self, limiter: Limiter = NO_LIMIT) -> Connection:
        _set_nodelay(self._writer)
        return Connection(AsyncioStream(self._reader, self._writer), limiter,
                          label="tcp")


class TcpListener(Listener):
    def __init__(self):
        self._accept_q: "asyncio.Queue" = asyncio.Queue()
        self._server: asyncio.AbstractServer = None
        self._closed = False
        self.bound_port: int = 0

    async def _on_client(self, reader, writer):
        await self._accept_q.put(_TcpUnfinalized(reader, writer))

    async def accept(self) -> UnfinalizedConnection:
        if self._closed:
            bail(ErrorKind.CONNECTION, "listener closed")
        item = await self._accept_q.get()
        if item is None:  # close() sentinel
            bail(ErrorKind.CONNECTION, "listener closed")
        return item

    async def close(self) -> None:
        self._closed = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._accept_q.put_nowait(None)  # wake any blocked accept()


def _uring_selected() -> bool:
    """True when the resolved io impl is the io_uring data plane. Imported
    lazily so the asyncio-only path never touches the native shim."""
    import os
    if os.environ.get("PUSHCDN_IO_IMPL", "") == "" \
            and os.environ.get("PUSHCDN_IO_URING", "") == "":
        return False  # default impl: skip the probe entirely
    from pushcdn_tpu.proto.transport import uring as uring_mod
    return uring_mod.resolve_io_impl() == "uring"


class Tcp(Protocol):
    name = "tcp"

    @classmethod
    async def connect(cls, endpoint: str, use_local_authority: bool = True,
                      limiter: Limiter = NO_LIMIT) -> Connection:
        host, port = parse_endpoint(endpoint)
        if _uring_selected():
            from pushcdn_tpu.proto.transport import uring as uring_mod
            try:
                async with asyncio.timeout(CONNECT_TIMEOUT_S):
                    return await uring_mod.uring_connect(
                        host, port, limiter, label=f"tcp:{endpoint}")
            except (OSError, asyncio.TimeoutError) as exc:
                bail(ErrorKind.CONNECTION,
                     f"tcp connect to {endpoint} failed", exc)
        try:
            async with asyncio.timeout(CONNECT_TIMEOUT_S):
                reader, writer = await asyncio.open_connection(host, port)
        except (OSError, asyncio.TimeoutError) as exc:
            bail(ErrorKind.CONNECTION, f"tcp connect to {endpoint} failed", exc)
        _set_nodelay(writer)
        return Connection(AsyncioStream(reader, writer), limiter,
                          label=f"tcp:{endpoint}")

    @classmethod
    async def bind(cls, endpoint: str, certificate=None,
                   reuse_port: bool = False) -> Listener:
        host, port = parse_endpoint(endpoint)
        if _uring_selected():
            from pushcdn_tpu.proto.transport import uring as uring_mod
            try:
                return uring_mod.uring_bind(host, port,
                                            reuse_port=reuse_port)
            except OSError as exc:
                bail(ErrorKind.CONNECTION,
                     f"tcp bind to {endpoint} failed", exc)
        listener = TcpListener()
        try:
            server = await asyncio.start_server(
                listener._on_client, host, port,
                **({"reuse_port": True} if reuse_port else {}))
        except (OSError, ValueError) as exc:
            bail(ErrorKind.CONNECTION, f"tcp bind to {endpoint} failed", exc)
        listener._server = server
        listener.bound_port = server.sockets[0].getsockname()[1]
        return listener
